"""Dashboard: single-file SPA served by the API server.

Reference analog: sky/dashboard/src/ (15.4k-LoC Next.js app with
clusters/jobs/services/infra pages and an xterm log viewer). Ours is a
dependency-free single-file app — the server renders one HTML shell
with the initial state embedded, and vanilla JS hash-routes between
list views and per-entity DETAIL pages (cluster job queue, managed-job
lifecycle, service replicas, per-cloud catalog), re-fetching
`/dashboard/api/*` for live data. Logs stream incrementally: the
viewer polls with a byte offset and appends only the new tail. With
token auth enabled, browsers authenticate through /dashboard/login
(HttpOnly cookie); API clients keep using bearer headers. No build
step: the whole UI ships in this module, works from `tsky api start`
with zero assets.
"""
import json
import os
from typing import Any, Dict, List, Optional

import skypilot_tpu
from skypilot_tpu.server import requests_db


def summary() -> Dict[str, Any]:
    """Everything the SPA's list views show, in one JSON document."""
    from skypilot_tpu import state as cluster_state
    from skypilot_tpu.utils import log_utils
    heartbeats = cluster_state.get_heartbeats()

    def _hb(rec):
        hb = heartbeats.get(rec['name'])
        return log_utils.heartbeat_str(hb['age_s'] if hb else None,
                                       rec['status'].value)

    clusters = [{
        'name': r['name'], 'workspace': r['workspace'],
        'status': r['status'].value, 'resources': r['resources_str'],
        'nodes': r['num_nodes'], 'heartbeat': _hb(r),
    } for r in cluster_state.get_clusters(all_workspaces=True)]

    jobs: List[Dict[str, Any]] = []
    try:
        from skypilot_tpu.jobs import state as jobs_state
        jobs = [{
            'id': j['job_id'], 'name': j['name'],
            'status': j['status'].value,
            'recoveries': j['recovery_count'],
            'log': f'/dashboard/jobs/{j["job_id"]}/log',
        } for j in jobs_state.get_jobs()]
    except Exception:  # noqa: BLE001 — jobs DB may not exist yet
        pass

    services: List[Dict[str, Any]] = []
    try:
        import urllib.parse
        from skypilot_tpu.serve import serve_state
        services = [{
            'name': s['name'], 'status': s['status'].value,
            'endpoint': f'http://127.0.0.1:{s["lb_port"]}',
            'log': ('/dashboard/services/'
                    + urllib.parse.quote(str(s['name']), safe='')
                    + '/log'),
        } for s in serve_state.get_services()]
    except Exception:  # noqa: BLE001
        pass

    requests = [{
        'id': r['request_id'], 'name': r['name'],
        'status': r['status'].value,
        'log': f'/dashboard/requests/{r["request_id"]}/log',
    } for r in requests_db.list_requests(50)]

    infra: List[Dict[str, Any]] = []
    try:
        from skypilot_tpu import check as check_lib
        from skypilot_tpu.utils.registry import CLOUD_REGISTRY
        enabled = set(check_lib.get_cached_enabled_clouds_or_refresh())
        infra = [{'cloud': name,
                  'enabled': name in enabled}
                 for name in sorted(CLOUD_REGISTRY.names())]
    except Exception:  # noqa: BLE001
        pass

    return {'version': skypilot_tpu.__version__, 'clusters': clusters,
            'jobs': jobs, 'services': services, 'requests': requests,
            'infra': infra}


# --- detail documents (one JSON per entity page) ---------------------------

def _cluster_detail(name: str) -> Optional[Dict[str, Any]]:
    from skypilot_tpu import state as cluster_state
    rec = cluster_state.get_cluster_from_name(name)
    if rec is None:
        return None
    out: Dict[str, Any] = {
        'kind': 'cluster', 'name': name,
        'fields': {
            'status': rec['status'].value,
            'workspace': rec['workspace'],
            'resources': rec['resources_str'],
            'nodes': rec['num_nodes'],
            'autostop': rec.get('autostop_str') or '-',
            'launched': rec.get('launched_at') or '-',
        },
        'shell': f'/dashboard/clusters/{name}/shell',
    }
    # The cluster's own job queue (skylet job table), newest first.
    try:
        handle = rec['handle']
        from skypilot_tpu.skylet import job_lib
        queue = job_lib.get_jobs(handle.runtime_dir)
        out['rows'] = {
            'title': 'job queue',
            'columns': ['id', 'name', 'status', 'exit_code',
                        'submitted'],
            'items': [{
                'id': j['job_id'], 'name': j.get('name') or '-',
                'status': j['status'].value,
                'exit_code': j.get('exit_code'),
                'submitted': j.get('submitted_at') or '-',
            } for j in reversed(queue)],
        }
    except Exception:  # noqa: BLE001 — remote/downed clusters
        out['rows'] = {'title': 'job queue',
                       'columns': ['id', 'name', 'status'],
                       'items': []}
    return out


def _job_detail(job_id: str) -> Optional[Dict[str, Any]]:
    try:
        jid = int(job_id)
    except ValueError:
        return None
    from skypilot_tpu.jobs import state as jobs_state
    rec = jobs_state.get_job(jid)
    if rec is None:
        return None
    return {
        'kind': 'job', 'name': f'managed job {jid}',
        'entity_id': jid,  # action payloads need the bare id
        'fields': {
            'name': rec['name'],
            'status': rec['status'].value,
            'recoveries': rec['recovery_count'],
            'cluster': rec.get('cluster_name') or '-',
            'submitted': rec.get('submitted_at') or '-',
        },
        'log': f'/dashboard/jobs/{jid}/log',
    }


def _service_detail(name: str) -> Optional[Dict[str, Any]]:
    import urllib.parse
    from skypilot_tpu.serve import serve_state
    rec = serve_state.get_service(name)
    if rec is None:
        return None
    replicas = []
    try:
        replicas = serve_state.get_replicas(name)
    except Exception:  # noqa: BLE001
        pass
    return {
        'kind': 'service', 'name': name,
        'fields': {
            'status': rec['status'].value,
            'endpoint': f'http://127.0.0.1:{rec["lb_port"]}',
            'policy': str(rec.get('policy') or '-'),
        },
        'rows': {
            'title': 'replicas',
            'columns': ['id', 'status', 'cluster', 'launched'],
            'items': [{
                'id': r.get('replica_id'),
                'status': (r['status'].value
                           if hasattr(r.get('status'), 'value')
                           else str(r.get('status'))),
                'cluster': r.get('cluster_name') or '-',
                'launched': r.get('launched_at') or '-',
            } for r in replicas],
        },
        'log': ('/dashboard/services/'
                + urllib.parse.quote(str(name), safe='') + '/log'),
    }


def _infra_detail(cloud: str) -> Optional[Dict[str, Any]]:
    from skypilot_tpu.utils.registry import CLOUD_REGISTRY
    if cloud not in CLOUD_REGISTRY.names():
        return None
    enabled = False
    try:
        from skypilot_tpu import check as check_lib
        enabled = cloud in set(
            check_lib.get_cached_enabled_clouds_or_refresh())
    except Exception:  # noqa: BLE001
        pass
    items: List[Dict[str, Any]] = []
    try:
        from skypilot_tpu.catalog import common as cat_common
        df = cat_common.read_catalog(cloud, 'vms')
        for row in list(df.itertuples())[:200]:
            info = cat_common.vm_row_to_info(cloud, row)
            items.append({
                'instance_type': info.instance_type,
                'accelerators': (f'{info.accelerator_name}:'
                                 f'{info.accelerator_count:g}'
                                 if info.accelerator_name else '-'),
                'cpus': info.cpus, 'memory_gb': info.memory_gb,
                'price': f'$ {info.price:.2f}',
                'region': info.region,
            })
    except Exception:  # noqa: BLE001 — catalog-less clouds (k8s, ssh)
        pass
    return {
        'kind': 'infra', 'name': cloud,
        'fields': {'enabled': 'enabled' if enabled else 'disabled',
                   'offerings': len(items)},
        'rows': {'title': 'catalog', 'columns': [
            'instance_type', 'accelerators', 'cpus', 'memory_gb',
            'price', 'region'], 'items': items},
    }


_DETAIL_FNS = {
    'clusters': _cluster_detail,
    'jobs': _job_detail,
    'services': _service_detail,
    'infra': _infra_detail,
}


def detail(kind: str, key: str) -> Optional[Dict[str, Any]]:
    fn = _DETAIL_FNS.get(kind)
    return fn(key) if fn is not None else None


_CSS = """
body{margin:0;font:13px/1.5 -apple-system,'Segoe UI',sans-serif;
     background:#0d1117;color:#c9d1d9}
header{display:flex;align-items:baseline;gap:16px;padding:10px 20px;
       background:#161b22;border-bottom:1px solid #30363d}
h1{font-size:16px;margin:0;color:#e6edf3}
h2{font-size:14px;margin:18px 0 4px;color:#e6edf3}
#ver{color:#8b949e;font-size:12px}
nav{display:flex;gap:4px;margin-left:auto}
nav button,#logout{background:none;border:none;color:#8b949e;
    padding:6px 12px;cursor:pointer;border-radius:6px;font-size:13px}
nav button.active{background:#21262d;color:#e6edf3}
#logout{color:#484f58}
main{padding:16px 20px;max-width:1100px}
table{border-collapse:collapse;width:100%;margin-top:8px}
th{font-size:11px;text-transform:uppercase;letter-spacing:.05em;
   color:#8b949e;text-align:left;padding:6px 10px;
   border-bottom:1px solid #30363d}
td{padding:6px 10px;border-bottom:1px solid #21262d}
tr:hover td{background:#161b22}
tr.click{cursor:pointer}
.chip{display:inline-block;padding:1px 8px;border-radius:10px;
      font-size:11px;font-weight:600}
.ok{background:#1a3524;color:#3fb950}.bad{background:#3d1418;
    color:#f85149}.warn{background:#3a2d12;color:#d29922}
.dim{background:#21262d;color:#8b949e}
a{color:#58a6ff;text-decoration:none}
.empty{color:#484f58;padding:14px 10px}
#updated{color:#484f58;font-size:11px;margin-top:14px}
dl{display:grid;grid-template-columns:140px 1fr;gap:4px 14px;
   margin:10px 0;max-width:560px}
dt{color:#8b949e}
dd{margin:0;color:#e6edf3}
.crumb{color:#8b949e;font-size:12px;margin-bottom:6px}
.toolbar{display:flex;gap:10px;align-items:center;margin-top:10px}
.toolbar input{background:#0d1117;color:#c9d1d9;
    border:1px solid #30363d;border-radius:6px;padding:5px 8px;
    font-size:12px;width:220px}
.count{color:#8b949e;font-size:11px}
th.sort{cursor:pointer;user-select:none}
th.sort:hover{color:#e6edf3}
.pager{display:flex;gap:8px;align-items:center;margin-top:10px;
    color:#8b949e;font-size:12px}
button.mini{background:#21262d;border:1px solid #30363d;
    color:#c9d1d9;padding:2px 8px;margin-right:4px;border-radius:6px;
    cursor:pointer;font-size:11px}
button.mini:hover{background:#30363d}
.adm-form{display:flex;gap:8px;margin-top:14px;align-items:center}
.adm-form input,.adm-form select{background:#0d1117;color:#c9d1d9;
    border:1px solid #30363d;border-radius:6px;padding:5px 8px;
    font-size:12px}
.adm-err{background:#3d1418;color:#f85149;padding:6px 10px;
    border-radius:6px;margin-bottom:8px;font-size:12px;
    white-space:pre-wrap}
pre.cfg{background:#161b22;border:1px solid #30363d;border-radius:6px;
    padding:12px;overflow:auto;font:12px/1.45 ui-monospace,monospace}
textarea.cfg-edit{width:100%;min-height:220px;background:#0d1117;
    color:#c9d1d9;border:1px solid #30363d;border-radius:6px;
    padding:10px;font:12px/1.45 ui-monospace,monospace;
    box-sizing:border-box}
"""

_JS = """
const OK=['UP','READY','RUNNING','SUCCEEDED','enabled'],
      BAD=['FAILED','FAILED_NO_RESOURCE','FAILED_CONTROLLER','NOT_READY'],
      TABS={clusters:['name','workspace','status','resources','nodes',
                      'heartbeat'],
            jobs:['id','name','status','recoveries','log'],
            services:['name','status','endpoint','log'],
            requests:['id','name','status','log'],
            infra:['cloud','enabled']},
      DETAIL_KEY={clusters:'name',jobs:'id',services:'name',
                  infra:'cloud'};
let state=window.__initial__;
function route(){
  const h=(location.hash||'#/clusters').slice(2).split('/');
  return {tab:h[0]||'clusters',
          key:h.length>1?decodeURIComponent(h.slice(1).join('/')):null}}
function chip(v){const s=String(v);
  const cls=OK.includes(s)?'ok':BAD.includes(s)?'bad':
    ['PENDING','PROVISIONING','RECOVERING','STARTING','INIT','STOPPED']
      .includes(s)?'warn':'dim';
  const e=document.createElement('span');e.className='chip '+cls;
  e.textContent=s;return e}
function cell(col,v){const td=document.createElement('td');
  if(col==='status')td.appendChild(chip(v));
  else if(col==='enabled')td.appendChild(chip(v?'enabled':'disabled'));
  else if(col==='log'){const a=document.createElement('a');
    a.href=v;a.textContent='view';
    a.addEventListener('click',e=>e.stopPropagation());
    td.appendChild(a)}
  else if(col==='endpoint'){const a=document.createElement('a');
    a.href=v;a.textContent=v;
    a.addEventListener('click',e=>e.stopPropagation());
    td.appendChild(a)}
  else td.textContent=v==null?'':v;
  return td}
// Per-tab table view state (filter text, sort column/direction,
// page). Lives outside the DOM so the 5s auto-refresh re-render
// can't reset what the user set up.
const PAGE_SIZE=25,VIEW={};
function view(tab){
  return VIEW[tab]||(VIEW[tab]={q:'',sort:null,dir:1,page:0})}
function rowText(r){return Object.values(r)
  .map(v=>String(v==null?'':v)).join(' ').toLowerCase()}
function cmp(a,b){
  const sa=String(a==null?'':a),sb=String(b==null?'':b);
  // Number(), not parseFloat(): '45s ago' must compare as a string,
  // not as 45 (units would invert the order vs '2m ago').
  const na=Number(sa),nb=Number(sb);
  if(sa!==''&&sb!==''&&!isNaN(na)&&!isNaN(nb))return na-nb;
  return sa.localeCompare(sb)}
function makeTable(cols,rows,clickTab,v){
  const table=document.createElement('table');
  const hr=document.createElement('tr');
  cols.forEach(c=>{const th=document.createElement('th');
    th.textContent=c+(v&&v.sort===c?(v.dir>0?' \\u25b2':' \\u25bc'):'');
    if(v){th.className='sort';
      th.addEventListener('click',()=>{
        if(v.sort===c)v.dir=-v.dir;else{v.sort=c;v.dir=1}
        renderList(clickTab)})}
    hr.appendChild(th)});
  table.appendChild(hr);
  rows.forEach(r=>{const tr=document.createElement('tr');
    cols.forEach(c=>tr.appendChild(cell(c,r[c])));
    if(clickTab&&DETAIL_KEY[clickTab]){tr.className='click';
      tr.addEventListener('click',()=>{location.hash=
        '#/'+clickTab+'/'+encodeURIComponent(r[DETAIL_KEY[clickTab]])})}
    table.appendChild(tr)});
  return table}
function renderList(tab){
  const m=document.getElementById('content');
  const act=document.activeElement;
  const hadFocus=act&&act.id==='flt';
  const caret=hadFocus?act.selectionStart:0;
  m.innerHTML='';
  const v=view(tab),all=state[tab]||[];
  const q=v.q.toLowerCase();
  let rows=q?all.filter(r=>rowText(r).includes(q)):all.slice();
  if(v.sort)rows.sort((a,b)=>cmp(a[v.sort],b[v.sort])*v.dir);
  const pages=Math.max(1,Math.ceil(rows.length/PAGE_SIZE));
  if(v.page>=pages)v.page=pages-1;
  const slice=rows.slice(v.page*PAGE_SIZE,(v.page+1)*PAGE_SIZE);
  const inp=el('input',{id:'flt',placeholder:'filter',value:v.q});
  inp.addEventListener('input',()=>{v.q=inp.value;v.page=0;
    renderList(tab)});
  m.appendChild(el('div',{class:'toolbar'},inp,
    el('span',{class:'count'},rows.length===all.length?
      String(all.length):rows.length+' of '+all.length)));
  if(slice.length)m.appendChild(makeTable(TABS[tab],slice,tab,v));
  else m.appendChild(el('div',{class:'empty'},
    q?'no matches':'nothing here yet'));
  if(pages>1)m.appendChild(el('div',{class:'pager'},
    btn('\\u2039 prev',()=>{if(v.page>0){v.page--;renderList(tab)}}),
    el('span',{},'page '+(v.page+1)+' / '+pages),
    btn('next \\u203a',()=>{
      if(v.page<pages-1){v.page++;renderList(tab)}})));
  if(hadFocus){const f=document.getElementById('flt');f.focus();
    const p=Math.min(caret,f.value.length);
    f.setSelectionRange(p,p)}}
function renderDetail(doc,tab){
  const m=document.getElementById('content');m.innerHTML='';
  const crumb=document.createElement('div');crumb.className='crumb';
  const back=document.createElement('a');back.href='#/'+tab;
  back.textContent='← '+tab;crumb.appendChild(back);
  m.appendChild(crumb);
  const h=document.createElement('h2');h.textContent=doc.name;
  m.appendChild(h);
  const dl=document.createElement('dl');
  Object.entries(doc.fields||{}).forEach(([k,v])=>{
    const dt=document.createElement('dt');dt.textContent=k;
    const dd=document.createElement('dd');
    if(k==='status'||k==='enabled')dd.appendChild(chip(v));
    else if(k==='endpoint'){const a=document.createElement('a');
      a.href=v;a.textContent=v;dd.appendChild(a)}
    else dd.textContent=v==null?'':v;
    dl.appendChild(dt);dl.appendChild(dd)});
  m.appendChild(dl);
  if(doc.log){const p=document.createElement('p');
    const a=document.createElement('a');a.href=doc.log;
    a.textContent='controller log';p.appendChild(a);m.appendChild(p)}
  if(doc.shell){const p=document.createElement('p');
    const a=document.createElement('a');a.href=doc.shell;
    a.textContent='open shell';p.appendChild(a);m.appendChild(p)}
  renderActions(m,doc,tab);
  if(doc.rows){const h2=document.createElement('h2');
    h2.textContent=doc.rows.title;m.appendChild(h2);
    if(doc.rows.items.length)
      m.appendChild(makeTable(doc.rows.columns,doc.rows.items,null));
    else{const d=document.createElement('div');d.className='empty';
      d.textContent='nothing here yet';m.appendChild(d)}}}
// --- entity actions (async commands; RBAC enforced server-side) ----------
const ACTIONS={
  clusters:[['stop','stop',d=>({cluster_name:d.name})],
            ['down','down',d=>({cluster_name:d.name})]],
  jobs:[['cancel','jobs_cancel',d=>({job_ids:[d.entity_id]})]],
  services:[['down','serve_down',d=>({service_name:d.name})]]};
// Fired actions survive the 5s auto-refresh re-render: a destructive
// button must not silently re-arm while its command is in flight.
const firedActions=new Set();
function renderActions(m,doc,tab){
  const acts=ACTIONS[tab]||[];
  if(!acts.length)return;
  const p=document.createElement('p');
  acts.forEach(([label,cmd,payload])=>{
    const key=tab+'/'+doc.name+'/'+label;
    const b=btn(label,async()=>{
      if(!confirm(label+' '+doc.name+'?'))return;
      b.disabled=true;firedActions.add(key);
      try{
        const body=await api('POST','/'+cmd,payload(doc));
        b.textContent=label+': request '+
          ((body&&body.request_id)||'sent');
        setTimeout(refresh,1500);
      }catch(e){
        firedActions.delete(key);b.disabled=false;showErr(m,e)}});
    if(firedActions.has(key)){
      b.disabled=true;b.textContent=label+': requested'}
    p.appendChild(b)});
  m.appendChild(p)}

// --- admin: workspaces + users (REST CRUD, admin-gated server-side) ------
async function api(method,path,body){
  const r=await fetch('/api/v1'+path,{method,
    headers:body?{'Content-Type':'application/json'}:{},
    body:body?JSON.stringify(body):undefined});
  if(r.status===401){location.href='/dashboard/login';throw 0}
  const text=await r.text();
  if(!r.ok)throw new Error(text||r.status);
  return text?JSON.parse(text):null}
function el(tag,attrs,...kids){const e=document.createElement(tag);
  Object.entries(attrs||{}).forEach(([k,v])=>{
    if(k==='onclick')e.addEventListener('click',v);
    else if(k==='class')e.className=v;else e[k]=v});
  kids.forEach(k=>e.appendChild(typeof k==='string'?
    document.createTextNode(k):k));return e}
function btn(label,fn){return el('button',{class:'mini',onclick:fn},label)}
function showErr(m,e){m.prepend(el('div',{class:'adm-err'},String(e)))}
async function renderWorkspaces(){
  const m=document.getElementById('content');m.innerHTML='';
  let rows;try{rows=await api('GET','/workspaces')}
  catch(e){showErr(m,e);return}
  const table=el('table',{},el('tr',{},...['name','clusters','storage',
    'allowed clouds','private','description',''].map(c=>el('th',{},c))));
  rows.forEach(w=>{
    table.appendChild(el('tr',{},
      el('td',{},w.name),
      el('td',{},String(w.active.clusters)),
      el('td',{},String(w.active.storage)),
      el('td',{},(w.allowed_clouds||[]).join(', ')||'(all)'),
      el('td',{},w.private?('yes: '+(w.allowed_users||[]).join(', '))
                :'no'),
      el('td',{},w.description||''),
      el('td',{},...(w.name==='default'?[]:[btn('delete',async()=>{
        if(!confirm('Delete workspace '+w.name+'?'))return;
        try{await api('DELETE','/workspaces/'+
          encodeURIComponent(w.name));renderWorkspaces()}
        catch(e){showErr(m,e)}})]))))});
  m.appendChild(table);
  const form=el('div',{class:'adm-form'},
    el('input',{id:'ws-name',placeholder:'name'}),
    el('input',{id:'ws-desc',placeholder:'description'}),
    el('input',{id:'ws-clouds',placeholder:'allowed clouds (a,b)'}),
    btn('create workspace',async()=>{
      const spec={};
      const d=document.getElementById('ws-desc').value;
      const c=document.getElementById('ws-clouds').value;
      if(d)spec.description=d;
      if(c)spec.allowed_clouds=c.split(',').map(s=>s.trim());
      try{await api('POST','/workspaces',
        {name:document.getElementById('ws-name').value,...spec});
        renderWorkspaces()}catch(e){showErr(m,e)}}));
  m.appendChild(form)}
async function renderUsers(){
  const m=document.getElementById('content');m.innerHTML='';
  let rows;try{rows=await api('GET','/users')}
  catch(e){m.innerHTML='<div class="empty">admin only</div>';return}
  const table=el('table',{},el('tr',{},...['name','role','workspace',
    'source','state',''].map(c=>el('th',{},c))));
  rows.forEach(u=>{
    const acts=[];
    if(u.source==='db'){
      acts.push(btn('rotate',async()=>{
        try{const doc=await api('POST','/users/'+
          encodeURIComponent(u.name)+'/rotate',{});
          alert('New token for '+u.name+' (shown once):\\n'+doc.token);
          renderUsers()}catch(e){showErr(m,e)}}));
      acts.push(btn(u.disabled?'enable':'disable',async()=>{
        try{await api('PUT','/users/'+encodeURIComponent(u.name),
          {disabled:!u.disabled});renderUsers()}
        catch(e){showErr(m,e)}}));
      acts.push(btn('delete',async()=>{
        if(!confirm('Delete user '+u.name+'?'))return;
        try{await api('DELETE','/users/'+encodeURIComponent(u.name));
          renderUsers()}catch(e){showErr(m,e)}}))}
    table.appendChild(el('tr',{},
      el('td',{},u.name),el('td',{},u.role),el('td',{},u.workspace),
      el('td',{},u.source),
      el('td',{},u.disabled?'disabled':'active'),
      el('td',{},...acts)))});
  m.appendChild(table);
  const form=el('div',{class:'adm-form'},
    el('input',{id:'u-name',placeholder:'name'}),
    el('select',{id:'u-role'},...['user','viewer','admin'].map(r=>
      el('option',{value:r},r))),
    el('input',{id:'u-ws',placeholder:'workspace',value:'default'}),
    btn('add user',async()=>{
      try{const doc=await api('POST','/users',
        {name:document.getElementById('u-name').value,
         role:document.getElementById('u-role').value,
         workspace:document.getElementById('u-ws').value});
        alert('Token for '+doc.name+' (shown once):\\n'+doc.token);
        renderUsers()}catch(e){showErr(m,e)}}));
  m.appendChild(form)}
async function renderConfig(){
  const m=document.getElementById('content');m.innerHTML='';
  try{const r=await fetch('/dashboard/api/config');
    if(r.status===401){location.href='/dashboard/login';return}
    if(!r.ok){m.innerHTML='<div class="empty">admin only</div>';return}
    const doc=await r.json();
    m.appendChild(el('div',{class:'crumb'},
      'effective config, all layers merged (secrets redacted)'));
    m.appendChild(el('pre',{class:'cfg'},doc.yaml));
    m.appendChild(el('h2',{},'edit '+doc.path));
    const ta=el('textarea',{class:'cfg-edit',spellcheck:false});
    ta.value=doc.raw;
    m.appendChild(ta);
    m.appendChild(el('div',{class:'adm-form'},
      btn('save (validates first; edits are live)',async()=>{
        try{
          const resp=await fetch('/dashboard/api/config',{
            method:'POST',
            headers:{'Content-Type':'application/json'},
            body:JSON.stringify({yaml:ta.value,etag:doc.etag})});
          if(resp.status===401){location.href='/dashboard/login';
            return}
          if(!resp.ok)throw new Error(await resp.text());
          renderConfig();
        }catch(e){showErr(m,e)}})));
  }catch(e){showErr(m,e)}}
async function render(){
  const {tab,key}=route();
  document.querySelectorAll('nav button').forEach(b=>
    b.classList.toggle('active',b.dataset.tab===tab));
  if(tab==='workspaces'){renderWorkspaces();return}
  if(tab==='users'){renderUsers();return}
  if(tab==='config'){renderConfig();return}
  if(key){
    try{const r=await fetch('/dashboard/api/'+tab+'/'+
        encodeURIComponent(key));
      if(r.status===401){location.href='/dashboard/login';return}
      if(r.ok){renderDetail(await r.json(),tab)}
      else{const m=document.getElementById('content');
        m.innerHTML='<div class="empty">not found</div>'}}
    catch(e){}
  }else{renderList(tab)}
  document.getElementById('updated').textContent=
    'updated '+new Date().toLocaleTimeString();
}
async function refresh(){
  try{const r=await fetch('/dashboard/api/summary');
    if(r.status===401){location.href='/dashboard/login';return}
    if(r.ok){state=await r.json();render()}}catch(e){}}
document.querySelectorAll('nav button').forEach(b=>
  b.addEventListener('click',()=>{location.hash='#/'+b.dataset.tab}));
window.addEventListener('hashchange',render);
document.getElementById('logout').addEventListener('click',()=>{
  location.href='/dashboard/logout'});
render();setInterval(refresh,5000);
"""


def script_embed(value: Any) -> str:
    """json.dumps for inline <script> blocks: a value containing
    '</script>' (e.g. a crafted cluster name or ?next= target —
    aiohttp decodes %2F in path segments) would terminate the script
    element and inject markup on the dashboard origin, and
    '<!--<script' sequences flip the HTML parser's script-data
    escaping states. \\uXXXX-escape the trigger characters — they can
    only occur inside JSON strings, where the escapes are valid."""
    return (json.dumps(value).replace('<', '\\u003c')
            .replace('>', '\\u003e').replace('&', '\\u0026'))


def page() -> str:
    initial = script_embed(summary())
    tabs = ''.join(
        f'<button data-tab="{t}">{label}</button>'
        for t, label in [('clusters', 'Clusters'),
                         ('jobs', 'Managed jobs'),
                         ('services', 'Services'),
                         ('requests', 'Requests'),
                         ('infra', 'Infra'),
                         ('workspaces', 'Workspaces'),
                         ('users', 'Users'),
                         ('config', 'Config')])
    return (
        '<!doctype html><html><head><title>skypilot-tpu</title>'
        f'<style>{_CSS}</style></head><body>'
        f'<header><h1>skypilot-tpu</h1>'
        f'<span id="ver">v{skypilot_tpu.__version__}</span>'
        f'<nav>{tabs}</nav>'
        '<button id="logout" title="log out">logout</button></header>'
        '<main><div id="content"></div><div id="updated"></div></main>'
        f'<script>window.__initial__={initial};{_JS}</script>'
        '</body></html>')


# --- login page -------------------------------------------------------------

_LOGIN_CSS = """
body{margin:0;display:grid;place-items:center;height:100vh;
     font:13px/1.5 -apple-system,'Segoe UI',sans-serif;
     background:#0d1117;color:#c9d1d9}
form{background:#161b22;border:1px solid #30363d;border-radius:8px;
     padding:28px 32px;display:flex;flex-direction:column;gap:12px;
     min-width:300px}
h1{font-size:15px;margin:0;color:#e6edf3}
input{background:#0d1117;border:1px solid #30363d;border-radius:6px;
      color:#e6edf3;padding:8px 10px;font-size:13px}
button{background:#238636;border:none;border-radius:6px;color:#fff;
       padding:8px;cursor:pointer;font-size:13px}
#err{color:#f85149;font-size:12px;min-height:16px;margin:0}
"""

_LOGIN_JS = """
document.querySelector('form').addEventListener('submit',async e=>{
  e.preventDefault();
  const token=document.getElementById('token').value.trim();
  const r=await fetch('/dashboard/api/login',{method:'POST',
    headers:{'Content-Type':'application/json'},
    body:JSON.stringify({token})});
  if(r.ok){location.href=window.__next__}
  else{document.getElementById('err').textContent=
    'invalid token';}
});
"""


def login_page(next_url: str = '/dashboard') -> str:
    return (
        '<!doctype html><html><head><title>skypilot-tpu login</title>'
        f'<style>{_LOGIN_CSS}</style></head><body>'
        '<form><h1>skypilot-tpu</h1>'
        '<input id="token" type="password" placeholder="API token" '
        'autofocus>'
        '<p id="err"></p><button type="submit">Sign in</button></form>'
        f'<script>window.__next__={script_embed(next_url)};{_LOGIN_JS}'
        '</script></body></html>')


_CLI_AUTH_JS = """
document.querySelector('button').addEventListener('click',async()=>{
  const err=document.getElementById('err');
  const r=await fetch('/dashboard/api/cli-auth?port='+window.__port__,
                      {method:'POST'});
  if(!r.ok){err.textContent='authorization failed ('+r.status+')';return}
  const body=await r.json();
  const delivery={token:body.token,state:window.__state__};
  let cb=null;
  try{
    // Token travels in the POST body to the CLI's loopback listener
    // (urlencoded = CORS simple request, no preflight) -- never in a
    // URL, so it can't land in browser history or proxy logs. The
    // state nonce proves this delivery answers the CLI's request.
    cb=await fetch(body.post,{method:'POST',
      body:new URLSearchParams(delivery)});
  }catch(e){
    // fetch THREW = the request never reached the listener (Chrome
    // Private Network Access blocks page->loopback from insecure
    // public origins before any preflight). Top-level redirects are
    // exempt, so fall back to one -- the only degraded path that
    // puts the token in a URL. An HTTP error (403 stale state etc.)
    // must NOT land here: the listener is reachable and re-sending
    // the token in a URL would only leak it.
    location.href=body.post+'?'+new URLSearchParams(delivery);
    return;
  }
  if(!cb.ok){err.textContent='the CLI listener rejected the '+
    'delivery ('+cb.status+') -- is another login running?';return}
  document.body.innerHTML='<form><h1>Logged in</h1>'+
    '<p style="color:#8b949e">You can close this tab and return '+
    'to the terminal.</p></form>';
});
"""


def cli_auth_page(port: int, state: str = '') -> str:
    """Explicit-consent page for `tsky api login --browser` (the
    same-origin POST is the CSRF boundary — see app._handle_cli_auth;
    `state` is the CLI's nonce, echoed through the token delivery)."""
    return (
        '<!doctype html><html><head><title>Authorize CLI</title>'
        f'<style>{_LOGIN_CSS}</style></head><body>'
        '<form onsubmit="return false"><h1>Authorize CLI sign-in?</h1>'
        f'<p style="color:#8b949e;margin:0">A `tsky api login '
        f'--browser` run on this machine (port {int(port)}) is asking '
        'for your API token. Only continue if you started it.</p>'
        '<p id="err"></p>'
        '<button type="button">Authorize</button></form>'
        f'<script>window.__port__={int(port)};'
        f'window.__state__={script_embed(state)};{_CLI_AUTH_JS}'
        '</script></body></html>')


# --- log viewer -------------------------------------------------------------

def tail_file(path: str, limit: int = 200_000) -> str:
    """Last `limit` bytes of a file without reading the whole thing."""
    try:
        with open(path, 'rb') as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - limit))
            return f.read().decode('utf-8', errors='replace')
    except FileNotFoundError:
        return '(no log yet)'


def read_from(path: str, offset: int, limit: int = 500_000
              ) -> Dict[str, Any]:
    """Incremental tail: bytes [offset, offset+limit) + the new offset
    (the follow-mode poller appends only what's new; a truncated/
    rotated file resets to a full tail)."""
    try:
        with open(path, 'rb') as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            if offset > size:  # truncated/rotated underneath us
                offset = 0
            f.seek(offset)
            data = f.read(limit)
            return {'text': data.decode('utf-8', errors='replace'),
                    'offset': offset + len(data), 'size': size}
    except FileNotFoundError:
        return {'text': '', 'offset': 0, 'size': 0}


_LOG_CSS = """
body{margin:0;background:#0d1117;color:#c9d1d9;
     font:12px/1.45 ui-monospace,Menlo,monospace}
header{position:sticky;top:0;display:flex;gap:14px;align-items:center;
       padding:8px 16px;background:#161b22;
       border-bottom:1px solid #30363d;font-family:sans-serif}
pre{margin:0;padding:12px 16px;white-space:pre-wrap;
    word-break:break-all}
a{color:#58a6ff;text-decoration:none}
label{color:#8b949e;font-size:12px}
"""

_LOG_JS = """
const pre=document.getElementById('log'),
      follow=document.getElementById('follow'),
      titleEl=document.getElementById('title');
let offset=window.__offset__;
async function poll(){
  try{const r=await fetch(location.pathname+'?raw=1&offset='+offset);
    if(r.status===401){location.href='/dashboard/login';return}
    if(r.ok){const t=await r.text();
      const title=r.headers.get('X-Log-Title');
      if(title&&title!==titleEl.textContent){
        titleEl.textContent=title;document.title=title}
      const next=parseInt(r.headers.get('X-Log-Offset')||offset);
      if(next<offset){pre.textContent=''}  // rotated: start over
      if(t){pre.textContent+=t;
        if(follow.checked)window.scrollTo(0,document.body.scrollHeight)}
      offset=next}}
  catch(e){}}
setInterval(poll,1500);
if(follow.checked)window.scrollTo(0,document.body.scrollHeight);
"""


def log_page(title: str, text: str, offset: int = 0) -> str:
    import html as html_lib
    return (
        '<!doctype html><html><head>'
        f'<title>{html_lib.escape(title)}</title>'
        f'<style>{_LOG_CSS}</style></head><body>'
        '<header><a href="/dashboard">&larr; dashboard</a>'
        f'<strong id="title">{html_lib.escape(title)}</strong>'
        '<label style="margin-left:auto">'
        '<input type="checkbox" id="follow" checked> follow</label>'
        '</header>'
        f'<pre id="log">{html_lib.escape(text)}</pre>'
        f'<script>window.__offset__={int(offset)};{_LOG_JS}'
        '</script></body></html>')


# --- in-browser shell -------------------------------------------------------

_TERM_CSS = """
body{margin:0;background:#0d1117;color:#c9d1d9;
     font:13px/1.5 -apple-system,'Segoe UI',sans-serif}
header{display:flex;gap:12px;padding:8px 16px;background:#161b22;
       border-bottom:1px solid #30363d;align-items:baseline}
a{color:#58a6ff;text-decoration:none}
#status{margin-left:auto;color:#8b949e;font-size:12px}
#term{margin:0;padding:10px 14px;white-space:pre;overflow:auto;
      height:calc(100vh - 56px);box-sizing:border-box;outline:none;
      font:13px/1.35 ui-monospace,'SF Mono',Menlo,monospace}
#term .cur{background:#c9d1d9;color:#0d1117}
"""

# A deliberately small terminal: enough VT handling for shells, REPLs
# and line editors (CR/LF/BS, CSI K/J/C/D/H, SGR stripped), speaking
# the ws proxy's raw-bytes protocol (server/ws_proxy.py). The
# reference ships xterm.js; ours is dependency-free by design — the
# whole dashboard is one self-contained document.
_TERM_JS = r"""
const term=document.getElementById('term'),
      status=document.getElementById('status');
let lines=[''],row=0,col=0;
function clamp(){if(row>=lines.length)lines.push('');
  if(col<0)col=0}
function put(ch){clamp();const l=lines[row];
  lines[row]=l.length>col?l.slice(0,col)+ch+l.slice(col+1)
    :l+' '.repeat(col-l.length)+ch;col++}
function csi(params,fin){const n=parseInt(params.split(';')[0]||'1');
  if(fin==='K'){clamp();lines[row]=lines[row].slice(0,col)}
  else if(fin==='J'){lines=[''];row=0;col=0}
  else if(fin==='H'){row=0;col=0}
  else if(fin==='C')col+=n;
  else if(fin==='D')col=Math.max(0,col-n)}
let esc='';
function write(text){
  for(const ch of text){
    if(esc){esc+=ch;
      if(esc[1]==='['){if(/[@-~]/.test(ch)){
        csi(esc.slice(2,-1),ch);esc=''}}
      else if(esc[1]===']'){if(ch==='\x07')esc=''}
      else esc='';
      continue}
    if(ch==='\x1b')esc=ch;
    else if(ch==='\n'){row++;clamp();col=0}
    else if(ch==='\r')col=0;
    else if(ch==='\b')col=Math.max(0,col-1);
    else if(ch==='\x07'){}
    else put(ch)}
  const over=lines.length-2000;
  if(over>0){lines=lines.slice(over);row=Math.max(0,row-over)}
  render()}
function render(){clamp();
  const out=lines.map((l,i)=>{
    if(i!==row)return l;
    const c=l.length>col?l[col]:' ';
    return l.slice(0,col)+'\x00'+c+'\x01'+l.slice(col+1)});
  term.textContent='';
  out.forEach((l,i)=>{
    const[pre,rest]=l.split('\x00');
    term.appendChild(document.createTextNode(pre??l));
    if(rest!==undefined){
      const[cur,post]=[rest.slice(0,1),rest.slice(2)];
      const s=document.createElement('span');s.className='cur';
      s.textContent=cur;term.appendChild(s);
      term.appendChild(document.createTextNode(post))}
    if(i<out.length-1)term.appendChild(document.createTextNode('\n'))});
  term.scrollTop=term.scrollHeight}
const proto=location.protocol==='https:'?'wss':'ws';
const cols=Math.max(20,Math.floor(term.clientWidth/7.8)),
      rows=Math.max(5,Math.floor(term.clientHeight/17.5));
const ws=new WebSocket(proto+'://'+location.host+
  '/api/v1/clusters/'+encodeURIComponent(window.__cluster__)+
  '/shell?rows='+rows+'&cols='+cols);
ws.binaryType='arraybuffer';
const dec=new TextDecoder(),enc=new TextEncoder();
ws.onopen=()=>{status.textContent='connected';term.focus()};
ws.onclose=()=>{status.textContent='disconnected'};
ws.onerror=()=>{status.textContent='connection failed'};
ws.onmessage=e=>{
  if(typeof e.data==='string'){
    if(e.data.startsWith('__SKYTPU_EXIT__'))
      status.textContent='shell exited ('+
        e.data.slice('__SKYTPU_EXIT__'.length)+')';
    return}
  write(dec.decode(new Uint8Array(e.data),{stream:true}))};
function send(s){if(ws.readyState===1)ws.send(enc.encode(s))}
const KEYS={Enter:'\r',Backspace:'\x7f',Tab:'\t',Escape:'\x1b',
  ArrowUp:'\x1b[A',ArrowDown:'\x1b[B',ArrowRight:'\x1b[C',
  ArrowLeft:'\x1b[D',Home:'\x1b[H',End:'\x1b[F',Delete:'\x1b[3~',
  PageUp:'\x1b[5~',PageDown:'\x1b[6~'};
term.addEventListener('keydown',e=>{
  if(e.ctrlKey&&e.key.length===1){
    const c=e.key.toLowerCase().charCodeAt(0);
    if(c>=97&&c<=122){send(String.fromCharCode(c-96));
      e.preventDefault();return}}
  if(e.metaKey||e.ctrlKey)return; // leave copy/paste etc. alone
  if(KEYS[e.key]){send(KEYS[e.key]);e.preventDefault()}
  else if(e.key.length===1){send(e.key);e.preventDefault()}});
term.addEventListener('paste',e=>{
  send(e.clipboardData.getData('text'));e.preventDefault()});
"""


def shell_page(cluster: str) -> str:
    """The in-browser terminal attached to the ws shell proxy
    (reference dashboard's xterm-based pod shell)."""
    import html as html_lib
    safe = html_lib.escape(cluster)
    return (
        '<!doctype html><html><head>'
        f'<title>shell: {safe}</title>'
        f'<style>{_TERM_CSS}</style></head><body>'
        '<header><a href="/dashboard">&larr; dashboard</a>'
        f'<strong>{safe}</strong>'
        '<span id="status">connecting…</span></header>'
        '<pre id="term" tabindex="0"></pre>'
        f'<script>window.__cluster__={script_embed(cluster)};'
        f'{_TERM_JS}</script></body></html>')


# --- config view ------------------------------------------------------------

_REDACT_KEYS = ('token', 'password', 'secret', 'key')


def _redact(obj):
    if isinstance(obj, dict):
        return {k: ('*****' if isinstance(v, str)
                    and any(s in k.lower() for s in _REDACT_KEYS)
                    else _redact(v))
                for k, v in obj.items()}
    if isinstance(obj, list):
        return [_redact(v) for v in obj]
    return obj


def config_doc() -> Dict[str, Any]:
    """The config page's document: the redacted EFFECTIVE (layered)
    view, plus the raw USER config file for the editor — editing the
    redacted view would clobber every secret on save, so the editor
    round-trips the file itself (admin-gated; an admin can read that
    file anyway)."""
    import yaml

    from skypilot_tpu import config as config_lib
    path = os.path.expanduser(config_lib.USER_CONFIG_PATH)
    try:
        with open(path, 'r', encoding='utf-8') as f:
            raw = f.read()
    except OSError:
        raw = ''
    import hashlib
    return {
        'path': config_lib.USER_CONFIG_PATH,
        'yaml': yaml.safe_dump(_redact(config_lib.to_dict()),
                               default_flow_style=False) or '',
        'raw': raw,
        # Editor concurrency token: a save against a stale snapshot
        # must 409, not silently revert another admin's change.
        'etag': hashlib.sha256(raw.encode()).hexdigest()[:16],
    }


class ConfigConflictError(ValueError):
    """The on-disk config changed since the editor loaded it."""


def _has_redacted_value(obj) -> bool:
    """A '*****' VALUE in the parsed config is the redacted view
    leaking into the editor (comments/banners with asterisks parse
    away and are fine)."""
    if isinstance(obj, dict):
        return any(_has_redacted_value(v) for v in obj.values())
    if isinstance(obj, list):
        return any(_has_redacted_value(v) for v in obj)
    return obj == '*****'


def save_config(text: str, expected_etag: str = '') -> None:
    """Validate + atomically write the USER config file (0600 from
    creation: it carries tokens). Raises ValueError with every schema
    violation listed — the editor shows them inline — and
    ConfigConflictError when the file changed since `expected_etag`
    was read (last-write-wins would silently revert another admin's
    token revocation)."""
    import hashlib
    import tempfile

    import yaml

    from skypilot_tpu import config as config_lib
    from skypilot_tpu import exceptions
    from skypilot_tpu.utils import schemas
    try:
        data = yaml.safe_load(text)
    except yaml.YAMLError as e:
        raise ValueError(f'Not valid YAML: {e}')
    if data is None:
        data = {}
    if not isinstance(data, dict):
        raise ValueError('Config must be a YAML mapping.')
    if _has_redacted_value(data):
        raise ValueError(
            "The config contains redacted '*****' values — saving "
            'them would destroy the real secrets. Edit the raw file '
            'content instead.')
    try:
        schemas.validate_config(data, path='(dashboard editor)')
    except exceptions.ConfigError as e:
        raise ValueError(str(e))
    path = os.path.expanduser(config_lib.USER_CONFIG_PATH)
    if expected_etag:
        try:
            with open(path, 'r', encoding='utf-8') as f:
                current = f.read()
        except OSError:
            current = ''
        if hashlib.sha256(
                current.encode()).hexdigest()[:16] != expected_etag:
            raise ConfigConflictError(
                'The config file changed since this editor loaded it; '
                'reload the page and re-apply your edit.')
    os.makedirs(os.path.dirname(path), exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path),
                               prefix='.config-edit-')
    try:
        os.fchmod(fd, 0o600)
        with os.fdopen(fd, 'w', encoding='utf-8') as f:
            f.write(text)
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    # Close the same-size/same-mtime_ns stat window on coarse-
    # timestamp filesystems: the save must be live NOW, in-process.
    config_lib.reload()
