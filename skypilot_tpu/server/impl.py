"""Server-side request implementations: the executor's function registry.

Reference analog: the reference executes sky/core.py + sky/execution.py
functions inside forked workers (sky/server/requests/executor.py:312);
this module is that binding layer — payload dict in, JSON-able result
out. Log output (provision progress, job logs) goes to the request log
file via the executor's fd redirection, which is what clients stream.
"""
import getpass
from typing import Any, Dict, List, Optional

from skypilot_tpu.server import executor


def _serialize_handle(handle) -> Optional[Dict[str, Any]]:
    if handle is None:
        return None
    return {
        'cluster_name': handle.cluster_name,
        'cluster_name_on_cloud': handle.cluster_name_on_cloud,
        'num_nodes': handle.num_nodes,
        'resources': repr(handle.launched_resources),
        'cloud': handle.cloud,
        'head_ip': handle.head_ip(),
    }


def _serialize_record(record: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(record)
    out['handle'] = _serialize_handle(record.get('handle'))
    status = out.get('status')
    if status is not None:
        out['status'] = status.value
    return out


def _load_task(payload: Dict[str, Any]):
    from skypilot_tpu import task as task_lib
    return task_lib.Task.from_yaml_config(payload['task'],
                                          env_overrides=payload.get('envs'))


@executor.register('launch')
def launch(payload: Dict[str, Any]) -> Dict[str, Any]:
    from skypilot_tpu import execution
    task = _load_task(payload)
    from skypilot_tpu import optimizer as optimizer_lib
    job_id, handle = execution.launch(
        task,
        cluster_name=payload['cluster_name'],
        dryrun=payload.get('dryrun', False),
        stream_logs=True,
        detach_run=payload.get('detach_run', False),
        no_setup=payload.get('no_setup', False),
        optimize_target=optimizer_lib.OptimizeTarget[
            payload.get('minimize', 'COST')],
        retry_until_up=payload.get('retry_until_up', False))
    return {'job_id': job_id, 'handle': _serialize_handle(handle)}


@executor.register('exec')
def exec_cmd(payload: Dict[str, Any]) -> Dict[str, Any]:
    from skypilot_tpu import execution
    task = _load_task(payload)
    job_id, handle = execution.exec_cmd(
        task, cluster_name=payload['cluster_name'],
        detach_run=payload.get('detach_run', False))
    return {'job_id': job_id, 'handle': _serialize_handle(handle)}


@executor.register('status')
def status(payload: Dict[str, Any]) -> List[Dict[str, Any]]:
    from skypilot_tpu import core
    records = core.status(cluster_names=payload.get('cluster_names'),
                          refresh=payload.get('refresh', False))
    return [_serialize_record(r) for r in records]


@executor.register('start')
def start(payload: Dict[str, Any]) -> None:
    from skypilot_tpu import core
    core.start(payload['cluster_name'],
               idle_minutes_to_autostop=payload.get('idle_minutes'),
               down=payload.get('down', False))


@executor.register('stop')
def stop(payload: Dict[str, Any]) -> None:
    from skypilot_tpu import core
    core.stop(payload['cluster_name'])


@executor.register('down')
def down(payload: Dict[str, Any]) -> None:
    from skypilot_tpu import core
    core.down(payload['cluster_name'], purge=payload.get('purge', False))


@executor.register('autostop')
def autostop(payload: Dict[str, Any]) -> None:
    from skypilot_tpu import core
    core.autostop(payload['cluster_name'], payload.get('idle_minutes'),
                  down_after=payload.get('down', False))


@executor.register('queue')
def queue(payload: Dict[str, Any]) -> List[Dict[str, Any]]:
    from skypilot_tpu import core
    return core.queue(payload['cluster_name'])


@executor.register('cancel')
def cancel(payload: Dict[str, Any]) -> Dict[str, Any]:
    from skypilot_tpu import core
    cancelled = core.cancel(payload['cluster_name'],
                            job_ids=payload.get('job_ids'),
                            all_jobs=payload.get('all_jobs', False))
    return {'cancelled': cancelled}


@executor.register('logs')
def logs(payload: Dict[str, Any]) -> Dict[str, Any]:
    """Job logs stream into THIS request's log file; clients stream it."""
    from skypilot_tpu import core
    rc = core.tail_logs(payload['cluster_name'],
                        job_id=payload.get('job_id'),
                        follow=payload.get('follow', True),
                        tail=payload.get('tail', 0))
    return {'exit_code': rc}


@executor.register('cost_report')
def cost_report(payload: Dict[str, Any]) -> List[Dict[str, Any]]:
    from skypilot_tpu import core
    out = []
    for row in core.cost_report():
        row = dict(row)
        if row.get('status') is not None:
            row['status'] = row['status'].value
        out.append(row)
    return out


@executor.register('check')
def check(payload: Dict[str, Any]):
    """Default (old clients): the enabled list. verbose=True adds the
    per-cloud probe detail; probe=True makes the authenticated calls
    (reference sky/check.py:53)."""
    from skypilot_tpu import check as check_lib
    enabled = check_lib.check(refresh=True, quiet=True,
                              probe=bool(payload.get('probe')))
    if not payload.get('verbose'):
        return enabled
    return {'enabled': enabled, 'details': check_lib.cached_details()}


@executor.register('optimize')
def optimize(payload: Dict[str, Any]) -> Dict[str, Any]:
    from skypilot_tpu import dag as dag_lib
    from skypilot_tpu import optimizer as optimizer_lib
    task = _load_task(payload)
    dag = dag_lib.Dag()
    dag.add(task)
    optimizer_lib.Optimizer.optimize(
        dag, minimize=optimizer_lib.OptimizeTarget[
            payload.get('minimize', 'COST')])
    chosen = task.best_resources
    return {
        'cloud': chosen.cloud,
        'instance_type': chosen.instance_type,
        'region': chosen.region,
        'zone': chosen.zone,
        'hourly_cost': getattr(chosen, '_hourly_cost', None),
    }


def server_user() -> str:
    try:
        return getpass.getuser()
    except (KeyError, OSError):  # pragma: no cover
        return 'unknown'


# --- managed jobs -----------------------------------------------------------

@executor.register('jobs_launch')
def jobs_launch(payload: Dict[str, Any]) -> Dict[str, Any]:
    from skypilot_tpu.jobs import core as jobs_core
    if payload.get('pipeline'):
        from skypilot_tpu import dag as dag_lib
        from skypilot_tpu import task as task_lib
        dag = dag_lib.Dag(name=payload.get('name'))
        prev = None
        for cfg in payload['pipeline']:
            stage = task_lib.Task.from_yaml_config(
                cfg, env_overrides=payload.get('envs'))
            dag.add(stage)
            if prev is not None:
                dag.add_edge(prev, stage)
            prev = stage
        target = dag
    else:
        target = _load_task(payload)
    job_id = jobs_core.launch(
        target, name=payload.get('name'),
        max_recoveries=payload.get('max_recoveries', 3),
        strategy=payload.get('strategy', 'EAGER_NEXT_REGION'))
    return {'job_id': job_id}


@executor.register('jobs_queue')
def jobs_queue(payload: Dict[str, Any]) -> List[Dict[str, Any]]:
    from skypilot_tpu.jobs import core as jobs_core
    return jobs_core.queue()


@executor.register('jobs_cancel')
def jobs_cancel(payload: Dict[str, Any]) -> Dict[str, Any]:
    from skypilot_tpu.jobs import core as jobs_core
    cancelled = jobs_core.cancel(job_ids=payload.get('job_ids'),
                                 all_jobs=payload.get('all_jobs', False))
    return {'cancelled': cancelled}


@executor.register('jobs_logs')
def jobs_logs(payload: Dict[str, Any]) -> Dict[str, Any]:
    from skypilot_tpu.jobs import core as jobs_core
    rc = jobs_core.tail_logs(payload['job_id'],
                             follow=payload.get('follow', True))
    return {'exit_code': rc}


# --- serve ------------------------------------------------------------------

@executor.register('serve_up')
def serve_up(payload: Dict[str, Any]) -> Dict[str, Any]:
    from skypilot_tpu.serve import core as serve_core
    task = _load_task(payload)
    return serve_core.up(task, payload['service_name'],
                         wait_seconds=payload.get('wait_seconds', 0.0))


@executor.register('serve_down')
def serve_down(payload: Dict[str, Any]) -> None:
    from skypilot_tpu.serve import core as serve_core
    serve_core.down(payload['service_name'],
                    purge=payload.get('purge', False))


@executor.register('serve_status')
def serve_status(payload: Dict[str, Any]) -> List[Dict[str, Any]]:
    from skypilot_tpu.serve import core as serve_core
    return serve_core.status(payload.get('service_names'))


@executor.register('serve_logs')
def serve_logs(payload: Dict[str, Any]) -> Dict[str, Any]:
    from skypilot_tpu.serve import core as serve_core
    rc = serve_core.tail_logs(payload['service_name'],
                              follow=payload.get('follow', True))
    return {'exit_code': rc}


@executor.register('serve_update')
def serve_update(payload: Dict[str, Any]) -> Dict[str, Any]:
    from skypilot_tpu.serve import core as serve_core
    task = _load_task(payload)
    return serve_core.update(task, payload['service_name'])


# --- storage ----------------------------------------------------------------

@executor.register('storage_ls')
def storage_ls(payload: Dict[str, Any]) -> List[Dict[str, Any]]:
    from skypilot_tpu import core
    return core.storage_ls()


@executor.register('storage_delete')
def storage_delete(payload: Dict[str, Any]) -> Dict[str, Any]:
    from skypilot_tpu import core
    deleted = core.storage_delete(names=payload.get('names'),
                                  all_storage=payload.get('all', False))
    return {'deleted': deleted}


@executor.register('accelerators')
def accelerators(payload: Dict[str, Any]) -> Dict[str, Any]:
    """Accelerator availability/pricing across clouds (reference
    `sky show-gpus`, catalog/__init__.py:57 list_accelerators)."""
    from skypilot_tpu import catalog
    out: Dict[str, Any] = {}
    for name, rows in catalog.list_accelerators(
            payload.get('name_filter')).items():
        out[name] = [{
            'cloud': r.cloud, 'instance_type': r.instance_type,
            'count': r.accelerator_count, 'price': r.price,
            'spot_price': r.spot_price, 'region': r.region,
        } for r in rows]
    return out
