"""Logging-agent interface: install + run fluent-bit on cluster hosts.

Reference analog: sky/logs/agent.py (FluentbitAgent: rendered config +
idempotent install command run during provisioning).
"""
import shlex
from typing import Dict, List

# Job logs live under the runtime dir; the agent tails all of them.
_FLUENTBIT_INSTALL = (
    'command -v fluent-bit >/dev/null 2>&1 || '
    '(curl -fsSL https://raw.githubusercontent.com/fluent/fluent-bit/'
    'master/install.sh | sh)')


class LoggingAgent:
    """One external logging backend (subclass per cloud)."""

    def fluentbit_output_config(self) -> Dict[str, str]:
        """The [OUTPUT] section key/values for this backend."""
        raise NotImplementedError

    def render_config(self, runtime_dir: str, cluster_name: str) -> str:
        """Full fluent-bit config tailing the cluster's job logs."""
        output = ''.join(f'    {k} {v}\n'
                         for k, v in
                         self.fluentbit_output_config().items())
        return (
            '[SERVICE]\n'
            '    Flush 5\n'
            '    Daemon off\n'
            '[INPUT]\n'
            '    Name tail\n'
            f'    Path {runtime_dir}/jobs/*/run.log\n'
            '    Tag  skytpu.job\n'
            '    Path_Key file\n'
            '[FILTER]\n'
            '    Name record_modifier\n'
            '    Match *\n'
            f'    Record cluster {cluster_name}\n'
            '[OUTPUT]\n'
            f'{output}')

    def setup_command(self, runtime_dir: str, cluster_name: str) -> str:
        """Idempotent shell: install fluent-bit, write config, (re)start
        the agent in the background."""
        config = self.render_config(runtime_dir, cluster_name)
        conf_path = f'{runtime_dir}/fluentbit.conf'
        pid_path = f'{runtime_dir}/fluentbit.pid'
        q_conf, q_pid = shlex.quote(conf_path), shlex.quote(pid_path)
        # Liveness via pidfile — a pgrep pattern would match the shell
        # running THIS command (its cmdline contains the pattern).
        return (
            f'{_FLUENTBIT_INSTALL} && '
            f'mkdir -p {shlex.quote(runtime_dir)} && '
            f'printf %s {shlex.quote(config)} > {q_conf} && '
            f'if ! (test -f {q_pid} && kill -0 $(cat {q_pid}) '
            f'2>/dev/null); then '
            f'nohup fluent-bit -c {q_conf} >/dev/null 2>&1 & '
            f'echo $! > {q_pid}; fi')


def setup_agent_on_cluster(runners: List, runtime_dir: str,
                           cluster_name: str) -> None:
    """Install + start the configured agent on every host (no-op when
    log shipping is disabled). Failures are non-fatal: a cluster
    without external logs is degraded, not broken."""
    from skypilot_tpu import logs as logs_lib
    from skypilot_tpu import sky_logging
    logger = sky_logging.init_logger(__name__)
    agent = logs_lib.get_logging_agent()
    if agent is None:
        return
    cmd = agent.setup_command(runtime_dir, cluster_name)
    for runner in runners:
        rc, out, err = runner.run(cmd, require_outputs=True)
        if rc != 0:
            logger.warning('Log-shipping agent setup failed on %s: %s',
                           runner.node_id, err or out)
