"""GCP Cloud Logging backend for task-log shipping.

Reference analog: sky/logs/gcp.py (fluentbit stackdriver output).
TPU-first choice: clusters are TPU-VMs with a default service account
that already holds logging.write, so no extra credential wiring is
needed — the fluent-bit stackdriver output uses the metadata server.
"""
from typing import Dict

from skypilot_tpu.logs import agent


class GcpLoggingAgent(agent.LoggingAgent):

    def fluentbit_output_config(self) -> Dict[str, str]:
        from skypilot_tpu import config as config_lib
        out = {
            'Name': 'stackdriver',
            'Match': '*',
            'Resource': 'global',
        }
        project = config_lib.get_nested(('logs', 'gcp', 'project_id'),
                                        default=None)
        if project:
            out['Project_ID'] = project
        return out
