"""External log shipping for task logs.

Reference analog: sky/logs/__init__.py:10 (get_logging_agent),
sky/logs/agent.py, sky/logs/gcp.py — a fluent-bit agent installed on
cluster hosts tails the job log directory and ships to a cloud logging
backend. Config:

    logs:
      store: gcp          # only backend implemented (TPU-first: logs
                          # land next to the TPUs in Cloud Logging)
      gcp:
        project_id: my-project
"""
from typing import Optional

from skypilot_tpu import exceptions


def get_logging_agent() -> Optional['agent.LoggingAgent']:
    """The configured agent, or None when shipping is disabled."""
    from skypilot_tpu import config as config_lib
    from skypilot_tpu.logs import agent as agent_lib
    from skypilot_tpu.logs import gcp as gcp_logs
    store = config_lib.get_nested(('logs', 'store'), default=None)
    if store is None:
        return None
    if store == 'gcp':
        return gcp_logs.GcpLoggingAgent()
    raise exceptions.InvalidTaskError(
        f'logs.store must be one of [gcp], got {store!r}')


from skypilot_tpu.logs import agent  # noqa: E402,F401 (re-export)
