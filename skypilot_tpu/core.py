"""Core API: status / start / stop / down / queue / cancel / logs /
autostop / cost-report.

Reference analog: sky/core.py (status :91, start :407, down :482,
stop :517, autostop :577, cancel :742).
"""
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import state
from skypilot_tpu.backends import gang_backend


def _backend() -> gang_backend.GangBackend:
    return gang_backend.GangBackend()


def _get_handle(cluster_name: str, *,
                require_up: bool = False) -> gang_backend.ClusterHandle:
    record = state.get_cluster_from_name(cluster_name)
    if record is None or record['handle'] is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} does not exist.')
    if require_up and record['status'] != state.ClusterStatus.UP:
        raise exceptions.ClusterNotUpError(
            f'Cluster {cluster_name!r} is {record["status"].value}.',
            cluster_status=record['status'])
    return record['handle']


def status(cluster_names: Optional[List[str]] = None,
           refresh: bool = False) -> List[Dict[str, Any]]:
    """Cluster records; with refresh=True, reconcile against the cloud
    (reference refresh_cluster_record backend_utils.py:2145)."""
    records = state.get_clusters()
    if cluster_names:
        names = set(cluster_names)
        records = [r for r in records if r['name'] in names]
        missing = names - {r['name'] for r in records}
        if missing:
            raise exceptions.ClusterDoesNotExist(
                f'Cluster(s) not found: {sorted(missing)}')
    if refresh:
        backend = _backend()
        for r in records:
            handle = r['handle']
            if handle is None:
                continue
            try:
                live = backend.query_status(handle)
            except Exception:  # noqa: BLE001 — cloud probe failure
                continue
            if live is None:
                # Gone from the cloud: drop the record.
                state.remove_cluster(r['name'], terminate=True)
                r['status'] = None
            elif live != r['status']:
                state.update_cluster_status(r['name'], live)
                r['status'] = live
        records = [r for r in records if r['status'] is not None]
    # Liveness telemetry (skylet HeartbeatEvent), attached AFTER any
    # refresh: reconciling a cluster to STOPPED drops its beat, and the
    # returned records must agree with that.
    heartbeats = state.get_heartbeats()
    for r in records:
        hb = heartbeats.get(r['name'])
        r['heartbeat_age_s'] = hb['age_s'] if hb else None
    return records


def start(cluster_name: str, idle_minutes_to_autostop: Optional[int] = None,
          down: bool = False) -> None:
    record = state.get_cluster_from_name(cluster_name)
    if record is None or record['handle'] is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} does not exist.')
    handle = record['handle']
    backend = _backend()

    class _Shim:
        num_nodes = handle.num_nodes
        name = cluster_name

    backend.provision(_Shim(), None, cluster_name=cluster_name)
    if idle_minutes_to_autostop is not None:
        backend.set_autostop(handle, idle_minutes_to_autostop, down)


def stop(cluster_name: str) -> None:
    handle = _get_handle(cluster_name)
    _backend().teardown(handle, terminate=False)


def down(cluster_name: str, purge: bool = False) -> None:
    handle = _get_handle(cluster_name)
    _backend().teardown(handle, terminate=True, purge=purge)


def autostop(cluster_name: str, idle_minutes: Optional[int],
             down_after: bool = False) -> None:
    handle = _get_handle(cluster_name, require_up=True)
    if idle_minutes is not None and idle_minutes < 0:
        idle_minutes = None  # negative == cancel, CLI sugar
    _backend().set_autostop(handle, idle_minutes, down_after)


def queue(cluster_name: str) -> List[Dict[str, Any]]:
    handle = _get_handle(cluster_name, require_up=True)
    return _backend().get_job_queue(handle)


def cancel(cluster_name: str, job_ids: Optional[List[int]] = None,
           all_jobs: bool = False) -> List[int]:
    handle = _get_handle(cluster_name, require_up=True)
    if not job_ids and not all_jobs:
        raise ValueError('Specify job_ids or all_jobs=True.')
    return _backend().cancel_jobs(handle, job_ids, cancel_all=all_jobs)


def tail_logs(cluster_name: str, job_id: Optional[int] = None,
              follow: bool = True, tail: int = 0) -> int:
    handle = _get_handle(cluster_name, require_up=True)
    return _backend().tail_logs(handle, job_id, follow=follow, tail=tail)


def cost_report() -> List[Dict[str, Any]]:
    """Per-cluster cost so far (live clusters + history)."""
    out = []
    now = time.time()
    for r in state.get_clusters():
        handle = r['handle']
        hourly = 0.0
        if handle is not None:
            hourly = getattr(handle.launched_resources, '_hourly_cost', 0.0)
            hourly *= handle.num_nodes
        duration = now - (r['launched_at'] or now)
        out.append({
            'name': r['name'],
            'status': r['status'],
            'duration_s': duration,
            'hourly_cost': hourly,
            'total_cost': hourly * duration / 3600.0,
        })
    for h in state.get_cluster_history():
        out.append({
            'name': h['name'] + ' (terminated)',
            'status': None,
            'duration_s': h['duration_s'],
            'hourly_cost': None,
            'total_cost': None,
        })
    return out


# --- storage ---------------------------------------------------------------

def storage_ls() -> List[Dict[str, Any]]:
    """Registered storage objects (reference sky storage ls)."""
    from skypilot_tpu import state as state_lib
    return state_lib.get_storage()


def storage_delete(names: Optional[List[str]] = None,
                   all_storage: bool = False) -> List[str]:
    """Delete storage objects: the backing bucket AND the record."""
    from skypilot_tpu import state as state_lib
    from skypilot_tpu.data import storage as storage_lib
    records = state_lib.get_storage()
    if not all_storage:
        wanted = set(names or [])
        records = [r for r in records if r['name'] in wanted]
        missing = wanted - {r['name'] for r in records}
        if missing:
            raise exceptions.StorageError(
                f'Storage not found: {sorted(missing)}')
    deleted = []
    for r in records:
        store = storage_lib.make_store(
            storage_lib.StoreType(r['store']), r['name'])
        try:
            store.delete()
        except exceptions.StorageError:
            pass  # bucket already gone: still drop the record
        state_lib.remove_storage(r['name'])
        deleted.append(r['name'])
    return deleted
