"""GangBackend: the engine — failover provisioning, gang job submission,
logs, teardown, autostop.

Reference analog: CloudVmRayBackend (sky/backends/cloud_vm_ray_backend.py:
2700, RetryingVmProvisioner :1143, handle :2189). TPU-first differences:
- No Ray anywhere: jobs go through the skylet CLI + gang runner
  (skylet/gang.py), which fans each logical node's command out to every
  host of its slice with jax.distributed/megascale coordinates.
- One failover engine drives both zones-within-cloud (here) and
  cloud-level retry (execution.py re-optimizes with blocked resources).
"""
import json
import os
import shlex
import tempfile
import time
import typing
from typing import Any, Dict, List, Optional

from skypilot_tpu import catalog
from skypilot_tpu import clouds as clouds_lib
from skypilot_tpu import exceptions
from skypilot_tpu import provision
from skypilot_tpu import resources as resources_lib
from skypilot_tpu import state
from skypilot_tpu.backends import backend as backend_lib
from skypilot_tpu.provision import common as provision_common
from skypilot_tpu.provision import provisioner
from skypilot_tpu.skylet import job_lib
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import registry

_WORKDIR_REMOTE = '~/sky_workdir'


class ClusterHandle(backend_lib.ResourceHandle):
    """Picklable cluster identity stored in the state DB."""

    def __init__(self, *, cluster_name: str, cluster_name_on_cloud: str,
                 launched_resources: resources_lib.Resources,
                 num_nodes: int,
                 cluster_info: Optional[provision_common.ClusterInfo] = None,
                 runtime_dir: Optional[str] = None):
        self.cluster_name = cluster_name
        self.cluster_name_on_cloud = cluster_name_on_cloud
        self.launched_resources = launched_resources
        self.num_nodes = num_nodes
        self.cluster_info = cluster_info
        self.runtime_dir = runtime_dir

    def get_cluster_name(self) -> str:
        return self.cluster_name

    @property
    def cloud(self) -> str:
        return self.launched_resources.cloud

    @property
    def provider_config(self) -> Dict[str, Any]:
        if self.cluster_info is None:
            return {}
        return self.cluster_info.provider_config

    def head_ip(self) -> Optional[str]:
        if self.cluster_info is None:
            return None
        head = self.cluster_info.get_head_instance()
        if head is None or not head.hosts:
            return None
        return head.hosts[0].get_ip()

    def __repr__(self) -> str:
        return (f'ClusterHandle({self.cluster_name!r}, '
                f'{self.launched_resources!r}, nodes={self.num_nodes})')


class RetryingProvisioner:
    """Zone-failover loop within one cloud (reference RetryingVmProvisioner
    :1143 / _retry_zones :1317, compressed: blocklists are (region, zone)
    tuples; cloud-level failover happens in execution.py)."""

    def __init__(self, cloud: clouds_lib.Cloud):
        self.cloud = cloud
        self.failover_history: List[Exception] = []

    def provision_with_retries(
            self, cluster_name: str, cluster_name_on_cloud: str,
            to_provision: resources_lib.Resources,
            num_nodes: int) -> provision_common.ProvisionRecord:
        # Defense in depth behind the optimizer's optimize-time
        # exclusion: callers that hand-build resources must not reach
        # a cloud that can't satisfy them (it would fail mid-provision
        # with a billed partial cluster).
        from skypilot_tpu.optimizer import Optimizer

        class _NodesOnly:
            def __init__(self, n):
                self.num_nodes = n
        gaps = Optimizer.capability_gaps(self.cloud,
                                         _NodesOnly(num_nodes),
                                         to_provision)
        if gaps:
            raise exceptions.NotSupportedError(
                f'{self.cloud.NAME} lacks required capabilities: '
                f'{", ".join(gaps)} (for {to_provision}).')
        rows = self.cloud.get_feasible(to_provision)
        if not rows:
            raise exceptions.ResourcesUnavailableError(
                f'No {self.cloud.NAME} offering for {to_provision}')
        tried = set()
        for row in rows:
            key = (row.region, row.zone)
            if key in tried:
                continue
            tried.add(key)
            variables = self.cloud.make_deploy_variables(
                to_provision.copy(
                    infra=f'{self.cloud.NAME}/{row.region}' +
                    (f'/{row.zone}' if row.zone else ''),
                    instance_type=row.instance_type,
                    _cluster_config_overrides=dict(
                        to_provision.cluster_config_overrides)),
                cluster_name_on_cloud, row.region, row.zone)
            config = provision_common.ProvisionConfig(
                provider_config=variables,
                authentication_config=self.cloud.authentication_config(),
                node_config={'use_spot': to_provision.use_spot},
                count=num_nodes,
                tags={'skytpu-cluster-name': cluster_name},
                ports_to_open_on_launch=list(to_provision.ports or []))
            try:
                record = provisioner.bulk_provision(
                    self.cloud.NAME, row.region, row.zone,
                    cluster_name_on_cloud, config)
                return record
            except exceptions.ProvisionError as e:
                self.failover_history.append(e)
                # Partial failure: clean up before the next zone
                # (reference teardown-on-failure in _retry_zones).
                try:
                    provision.terminate_instances(
                        self.cloud.NAME, cluster_name_on_cloud, variables)
                except Exception:  # noqa: BLE001
                    pass
                if not e.retryable:
                    break
        raise exceptions.ResourcesUnavailableError(
            f'Failed to provision {to_provision} on {self.cloud.NAME} in '
            f'all {len(tried)} zone(s).',
            failover_history=self.failover_history)


@registry.BACKEND_REGISTRY.register(name='gang')
class GangBackend(backend_lib.Backend[ClusterHandle]):
    NAME = 'gang'

    # --- provision ----------------------------------------------------------

    def provision(self, task, to_provision, *, dryrun=False,
                  stream_logs=True,
                  cluster_name: str) -> Optional[ClusterHandle]:
        common_utils.check_cluster_name_is_valid(cluster_name)
        if dryrun:
            return None
        record = state.get_cluster_from_name(cluster_name)
        if record is not None and record['handle'] is not None:
            handle = record['handle']
            if record['status'] == state.ClusterStatus.UP:
                self._check_existing_satisfies(handle, to_provision, task)
                return handle
            # STOPPED / INIT: re-provision in place (resume).
            to_provision = handle.launched_resources
        to_provision.assert_launchable()
        cloud = clouds_lib.get_cloud(to_provision.cloud)
        max_len = cloud.MAX_CLUSTER_NAME_LENGTH or 64
        cluster_name_on_cloud = common_utils.make_cluster_name_on_cloud(
            cluster_name, max_len)

        prov = RetryingProvisioner(cloud)
        record_p = prov.provision_with_retries(
            cluster_name, cluster_name_on_cloud, to_provision,
            task.num_nodes)
        launched = to_provision.copy(
            infra=f'{cloud.NAME}/{record_p.region}' +
            (f'/{record_p.zone}' if record_p.zone else ''))
        launched._hourly_cost = getattr(  # noqa: SLF001
            to_provision, '_hourly_cost', 0.0)
        cluster_info = provision.get_cluster_info(
            cloud.NAME, record_p.region, cluster_name_on_cloud,
            self._deploy_variables(cloud, launched, cluster_name_on_cloud,
                                   record_p))
        rt, epoch = provisioner.post_provision_runtime_setup(
            cloud.NAME, cluster_name, cluster_info,
            stream_logs=stream_logs)
        handle = ClusterHandle(
            cluster_name=cluster_name,
            cluster_name_on_cloud=cluster_name_on_cloud,
            launched_resources=launched,
            num_nodes=task.num_nodes,
            cluster_info=cluster_info,
            runtime_dir=rt)
        cluster_hash = common_utils.deterministic_hash({
            'cloud': cloud.NAME, 'region': record_p.region,
            'zone': record_p.zone,
            'instance_type': launched.instance_type,
            'num_nodes': task.num_nodes,
        })
        state.add_or_update_cluster(
            cluster_name, handle,
            repr(launched), task.num_nodes, ready=True,
            cluster_hash=cluster_hash, epoch=epoch)
        self._maybe_set_autostop(handle, launched)
        return handle

    def _deploy_variables(self, cloud, launched, cluster_name_on_cloud,
                          record_p) -> Dict[str, Any]:
        return cloud.make_deploy_variables(
            launched, cluster_name_on_cloud, record_p.region, record_p.zone)

    def _check_existing_satisfies(self, handle: ClusterHandle,
                                  to_provision, task=None) -> None:
        have = handle.launched_resources
        if to_provision is not None:
            wants = [to_provision]
        elif task is not None and getattr(task, 'resources', None):
            # Reuse path: the task's (possibly partial) request must be
            # satisfiable by what the cluster already has.
            wants = [c for r in task.resources
                     for c in r.get_candidate_set()]
        else:
            return
        if not any(w.less_demanding_than(have) for w in wants):
            raise exceptions.ResourcesMismatchError(
                f'Cluster {handle.cluster_name!r} has {have}, which does '
                f'not satisfy the request {wants}. Tear it down first or '
                'use a new cluster name.')

    def _maybe_set_autostop(self, handle: ClusterHandle,
                            launched: resources_lib.Resources) -> None:
        autostop = launched.autostop
        if autostop is None or not autostop.enabled:
            # UNSET gets the local default; an EXPLICIT opt-out
            # (autostop: false) is the user saying "stay up" and wins.
            if autostop is None and str(launched.cloud) == 'local':
                self._set_default_local_autostop(handle)
            return
        # TPU slices cannot stop — force down (reference
        # clouds/gcp.py:216-226).
        down = autostop.down or launched.is_tpu
        self.set_autostop(handle, autostop.idle_minutes, down)

    def _set_default_local_autostop(self, handle: ClusterHandle) -> None:
        """Local-cloud clusters run on the user's OWN machine, and an
        abandoned session would leave its skylet ticking forever (the
        hygiene contract says zero daemons after the work is gone).
        Default: terminate after local.default_autostop_minutes idle
        (4h if unset; 0 disables). Explicit user autostop wins."""
        from skypilot_tpu import config as config_lib
        minutes = config_lib.get_nested(
            ('local', 'default_autostop_minutes'), default=240)
        try:
            minutes = float(minutes)
        except (TypeError, ValueError):
            return
        if minutes <= 0:
            return
        self.set_autostop(handle, minutes, down=True)

    # --- sync ---------------------------------------------------------------

    def sync_workdir(self, handle: ClusterHandle, workdir: str) -> None:
        runners = self._runners(handle)
        src = common_utils.expand_path(workdir).rstrip('/') + '/'
        for runner in runners:
            runner.rsync(src, f'{_WORKDIR_REMOTE}/', up=True,
                         excludes=['.git'])

    def sync_file_mounts(self, handle: ClusterHandle, file_mounts,
                         storage_mounts=None) -> None:
        runners = self._runners(handle)
        for dst, src in (file_mounts or {}).items():
            if src.startswith(('s3://', 'gs://', 'r2://', 'https://',
                               'http://')):
                self._download_remote_source(runners, src, dst)
                continue
            src_path = common_utils.expand_path(src)
            if os.path.isdir(src_path):
                src_path = src_path.rstrip('/') + '/'
                dst = dst.rstrip('/') + '/'
            for runner in runners:
                runner.rsync(src_path, dst, up=True)
        if storage_mounts:
            from skypilot_tpu.data import storage_mounting
            specs = {
                dst: (s.mount_spec() if hasattr(s, 'mount_spec') else s)
                for dst, s in storage_mounts.items()
            }
            storage_mounting.mount_all(runners, specs)

    def _download_remote_source(self, runners, src: str, dst: str) -> None:
        if src.startswith('gs://'):
            cmd = f'mkdir -p $(dirname {dst}) && gsutil -m cp -r ' \
                  f'{shlex.quote(src)} {shlex.quote(dst)}'
        elif src.startswith('s3://'):
            cmd = f'mkdir -p $(dirname {dst}) && aws s3 cp --recursive ' \
                  f'{shlex.quote(src)} {shlex.quote(dst)}'
        else:
            cmd = f'mkdir -p $(dirname {dst}) && curl -fsSL ' \
                  f'{shlex.quote(src)} -o {shlex.quote(dst)}'
        for runner in runners:
            rc, out, err = runner.run(cmd, require_outputs=True)
            if rc != 0:
                raise exceptions.CommandError(rc, cmd, err or out)

    # --- execute ------------------------------------------------------------

    def execute(self, handle: ClusterHandle, task, *, detach_run=False,
                dryrun=False, include_setup: bool = True) -> Optional[int]:
        if dryrun:
            return None
        if task.num_nodes > handle.num_nodes:
            raise exceptions.ResourcesMismatchError(
                f'Task needs {task.num_nodes} nodes but cluster '
                f'{handle.cluster_name!r} has {handle.num_nodes}.')
        launched = handle.launched_resources
        accs = launched.accelerators or {}
        acc_str = ','.join(f'{n}:{int(c) if c == int(c) else c}'
                           for n, c in accs.items())
        run_cmd = task.run if isinstance(task.run, str) else None
        spec: Dict[str, Any] = {
            'name': task.name or '-',
            'num_nodes': task.num_nodes,
            'run': self._wrap_user_cmd(run_cmd),
            'setup': (self._wrap_user_cmd(task.setup)
                      if include_setup and task.setup else None),
            'envs': task.envs_and_secrets,
            'is_tpu': launched.is_tpu,
            'accelerators_per_node': acc_str,
            'resources_str': acc_str or launched.instance_type or '',
        }
        job_id = self._submit_spec(handle, spec)
        state.update_last_use(handle.cluster_name)
        if not detach_run:
            rc = self.tail_logs(handle, job_id)
            if rc != 0:
                raise exceptions.JobExitNonZeroError(
                    f'Job {job_id} on {handle.cluster_name!r} failed with '
                    f'exit code {rc}. Check `tsky logs '
                    f'{handle.cluster_name} {job_id}`.')
        return job_id

    @staticmethod
    def _wrap_user_cmd(cmd: Optional[str]) -> Optional[str]:
        if cmd is None:
            return None
        # Run from the synced workdir when it exists.
        return (f'mkdir -p {_WORKDIR_REMOTE} && cd {_WORKDIR_REMOTE} && '
                f'{cmd}')

    def _submit_spec(self, handle: ClusterHandle,
                     spec: Dict[str, Any]) -> int:
        head = self._runners(handle)[0]
        rt = handle.runtime_dir
        with tempfile.NamedTemporaryFile('w', suffix='.json',
                                         delete=False) as f:
            json.dump(spec, f)
            local_spec = f.name
        try:
            remote_spec = f'/tmp/skytpu_spec_{os.path.basename(local_spec)}'
            head.rsync(local_spec, remote_spec, up=True)
            cmd = provisioner.skylet_cli_cmd_for(
                head, rt, 'submit', '--spec-file', remote_spec)
            rc, out, err = head.run(cmd, require_outputs=True)
            if rc != 0:
                raise exceptions.CommandError(rc, cmd, err or out)
            return int(json.loads(out.strip().splitlines()[-1])['job_id'])
        finally:
            os.unlink(local_spec)

    # --- job control --------------------------------------------------------

    def tail_logs(self, handle: ClusterHandle, job_id: Optional[int], *,
                  follow: bool = True, tail: int = 0) -> int:
        head = self._runners(handle)[0]
        args = []
        if job_id is not None:
            args += ['--job-id', str(job_id)]
        if not follow:
            args += ['--no-follow']
        if tail:
            args += ['--tail', str(tail)]
        cmd = provisioner.skylet_cli_cmd_for(
            head, handle.runtime_dir, 'tail', *args)
        rc = head.run(cmd, stream_logs=True)
        return rc if isinstance(rc, int) else rc[0]

    def cancel_jobs(self, handle: ClusterHandle, job_ids=None,
                    cancel_all: bool = False) -> List[int]:
        head = self._runners(handle)[0]
        args = []
        if cancel_all:
            args.append('--all')
        elif job_ids:
            args += ['--job-ids'] + [str(j) for j in job_ids]
        cmd = provisioner.skylet_cli_cmd_for(
            head, handle.runtime_dir, 'cancel', *args)
        rc, out, err = head.run(cmd, require_outputs=True)
        if rc != 0:
            raise exceptions.CommandError(rc, cmd, err or out)
        return json.loads(out.strip().splitlines()[-1])['cancelled']

    def get_job_queue(self, handle: ClusterHandle) -> List[Dict[str, Any]]:
        head = self._runners(handle)[0]
        cmd = provisioner.skylet_cli_cmd_for(
            head, handle.runtime_dir, 'queue')
        rc, out, err = head.run(cmd, require_outputs=True)
        if rc != 0:
            raise exceptions.CommandError(rc, cmd, err or out)
        return json.loads(out.strip().splitlines()[-1])

    def set_autostop(self, handle: ClusterHandle,
                     idle_minutes: Optional[int], down: bool) -> None:
        head = self._runners(handle)[0]
        args = []
        if idle_minutes is None:
            args.append('--cancel')
        else:
            args += ['--idle-minutes', str(idle_minutes)]
        if down:
            args.append('--down')
        args += ['--provider-name', handle.cloud,
                 '--cluster-name-on-cloud', handle.cluster_name_on_cloud,
                 '--provider-config', json.dumps(handle.provider_config)]
        cmd = provisioner.skylet_cli_cmd_for(
            head, handle.runtime_dir, 'set-autostop', *args)
        rc, out, err = head.run(cmd, require_outputs=True)
        if rc != 0:
            raise exceptions.CommandError(rc, cmd, err or out)
        state.set_autostop(
            handle.cluster_name,
            None if idle_minutes is None else
            {'idle_minutes': idle_minutes, 'down': down})

    # --- teardown -----------------------------------------------------------

    def teardown(self, handle: ClusterHandle, *, terminate: bool,
                 purge: bool = False) -> None:
        cloud = clouds_lib.get_cloud(handle.cloud)
        if not terminate:
            supports = getattr(cloud, 'supports_for', None)
            can_stop = (supports(clouds_lib.CloudCapability.STOP,
                                 handle.launched_resources)
                        if supports else
                        cloud.supports(clouds_lib.CloudCapability.STOP))
            if not can_stop:
                raise exceptions.NotSupportedError(
                    f'{handle.cluster_name}: stopping is not supported for '
                    f'{handle.launched_resources} (TPU slices can only be '
                    'terminated). Use `tsky down`.')
        try:
            provisioner.teardown_cluster(
                handle.cloud, handle.cluster_name_on_cloud,
                handle.provider_config, terminate)
        except Exception:  # noqa: BLE001
            if not purge:
                raise
        state.remove_cluster(handle.cluster_name, terminate=terminate)

    # --- status refresh ------------------------------------------------------

    def query_status(self, handle: ClusterHandle
                     ) -> Optional[state.ClusterStatus]:
        """Reconcile cloud truth -> ClusterStatus (reference
        _update_cluster_status backend_utils.py:1830)."""
        statuses = provision.query_instances(
            handle.cloud, handle.cluster_name_on_cloud,
            handle.provider_config)
        if not statuses:
            return None  # gone from the cloud
        vals = set(statuses.values())
        if vals == {'running'}:
            return state.ClusterStatus.UP
        if 'running' in vals:
            return state.ClusterStatus.INIT  # partially up: abnormal
        if vals <= {'stopped', 'stopping'}:
            return state.ClusterStatus.STOPPED
        return state.ClusterStatus.INIT

    # --- helpers ------------------------------------------------------------

    def _runners(self, handle: ClusterHandle):
        if handle.cluster_info is None:
            raise exceptions.ClusterNotUpError(
                f'Cluster {handle.cluster_name!r} has no reachable hosts '
                '(still INIT?).')
        return provision.get_command_runners(handle.cloud,
                                             handle.cluster_info)
