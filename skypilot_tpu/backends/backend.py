"""Backend interface: provision/sync/setup/execute/teardown lifecycle.

Reference analog: sky/backends/backend.py:30 (`Backend`, `ResourceHandle`
:24).
"""
from typing import Any, Dict, Generic, Optional, TypeVar

from skypilot_tpu import resources as resources_lib


class ResourceHandle:
    """Opaque, picklable identity of a provisioned cluster."""

    def get_cluster_name(self) -> str:
        raise NotImplementedError


H = TypeVar('H', bound=ResourceHandle)


class Backend(Generic[H]):
    NAME = 'backend'

    # --- lifecycle ----------------------------------------------------------

    def provision(self, task, to_provision: Optional[
            resources_lib.Resources], *, dryrun: bool = False,
            stream_logs: bool = True, cluster_name: str,
            ) -> Optional[H]:
        raise NotImplementedError

    def sync_workdir(self, handle: H, workdir: str) -> None:
        raise NotImplementedError

    def sync_file_mounts(self, handle: H,
                         file_mounts: Optional[Dict[str, str]],
                         storage_mounts: Optional[Dict[str, Any]]) -> None:
        raise NotImplementedError

    def execute(self, handle: H, task, *, detach_run: bool = False,
                dryrun: bool = False) -> Optional[int]:
        """Submit the task as a job; returns job id."""
        raise NotImplementedError

    def teardown(self, handle: H, *, terminate: bool,
                 purge: bool = False) -> None:
        raise NotImplementedError

    # --- job control --------------------------------------------------------

    def tail_logs(self, handle: H, job_id: Optional[int], *,
                  follow: bool = True, tail: int = 0) -> int:
        raise NotImplementedError

    def cancel_jobs(self, handle: H, job_ids=None,
                    cancel_all: bool = False):
        raise NotImplementedError

    def get_job_queue(self, handle: H):
        raise NotImplementedError
