"""TPU compute ops: attention variants (dense/blockwise/ring/flash)."""
from skypilot_tpu.ops import attention  # submodule, keep unshadowed
from skypilot_tpu.ops.attention import (blockwise_attention, dense_attention,
                                        ring_attention)

# Dispatching entry point (impl='dense'|'blockwise'|'ring'|'flash').
attention_fn = attention.attention

__all__ = ['attention', 'attention_fn', 'blockwise_attention',
           'dense_attention', 'ring_attention']
