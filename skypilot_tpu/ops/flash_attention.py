"""Fused flash attention as Pallas TPU kernels — forward AND backward.

Forward: grid over (batch, head, q-block, kv-block), online-softmax
accumulators live in VMEM scratch that persists across the sequential
innermost grid dimension (TPU grids are sequential, so the kv loop
accumulates in-place), and the [bq, bk] score tile never leaves VMEM —
HBM traffic is O(S·D) instead of O(S²). The forward also emits the
per-row logsumexp so backward never re-runs the softmax reduction.

Backward: the FlashAttention-2 formulation with two kernels —
  dq: grid (b, h, q-block, kv-block), dq accumulates in VMEM across
      the sequential kv dimension;
  dkv: grid (b, h, kv-block, q-block), dk/dv accumulate across the
      sequential q dimension. GQA head groups are summed outside.
Both recompute p = exp(s - lse) from the saved logsumexp (one extra
matmul, no stored probs) and apply delta = rowsum(do·o).

GQA is folded into the index maps: kv blocks for head h come from kv
head h // (num_heads // num_kv_heads), so no materialized repeat.

Sliding windows (Mistral, Gemma-2 local layers): the window size is a
RUNTIME int32 scalar living in SMEM, because the model stacks scan one
compiled layer body over a per-layer window schedule
(models/llama.py `layer_windows` — traced values, one compilation).
Block pairs with no (q_pos, k_pos) satisfying
`k_pos <= q_pos < k_pos + window` skip their matmuls entirely via
`pl.when`, so a 4k window over a 32k sequence does ~window/seq of the
full-causal FLOPs. Gemma attn-logit softcapping (cap·tanh(s/cap)) is a
static per-model constant compiled into the kernel; backward folds the
(1 - tanh²) Jacobian into ds.

No reference equivalent (SkyPilot ships no kernels; SURVEY.md §2.11).
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _score_mods(s, q_start, k_start, w_ref, *, causal, windowed, softcap,
                bq, bk):
    """Softcap then mask a [bq, bk] score tile; returns (s, tanh_t).

    tanh_t is the pre-mask tanh(s/cap) the backward kernels need for the
    softcap Jacobian (None when softcap is off).

    w_ref is the 2-element SMEM scalar block [window, q_offset]:
    q_offset shifts every query's GLOBAL position (cached-prefill
    chunks attend a cache much longer than the chunk; a chunk starting
    at cache position `off` must mask as if its rows were rows
    off..off+bq). Square training attention passes offset 0.
    """
    t = None
    if softcap is not None:
        t = jnp.tanh(s / softcap)
        s = softcap * t
    if causal or windowed:
        q_pos = (w_ref[1] + q_start +
                 lax.broadcasted_iota(jnp.int32, (bq, bk), 0))
        k_pos = k_start + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        mask = None
        if causal:
            mask = q_pos >= k_pos
        if windowed:
            wm = q_pos - k_pos < w_ref[0]
            mask = wm if mask is None else mask & wm
        s = jnp.where(mask, s, _NEG_INF)
    return s, t


def _block_visible(q_start, k_start, w_ref, *, causal, windowed, bq, bk):
    """Traced predicate: does ANY (q, k) pair in this block tile satisfy
    the causal+window mask `k <= q < k + window`? The valid k-range for
    the q tile is (q_start - window, q_start + bq - 1]; overlap with the
    k tile gives the two comparisons below. Query positions are global:
    local tile row + w_ref[1] offset."""
    cond = None
    if causal:
        cond = k_start < w_ref[1] + q_start + bq
    if windowed:
        wc = k_start + bk + w_ref[0] > w_ref[1] + q_start + 1
        cond = wc if cond is None else cond & wc
    return cond  # None = statically always visible


def _fwd_kernel(w_ref, q_ref, k_ref, v_ref, *rest, causal: bool,
                windowed: bool, softcap: Optional[float], scale: float,
                bq: int, bk: int, n_kv_blocks: int,
                quant: bool = False):
    # quant mode (int8 KV cache, engine.quantize_kv): k/v arrive int8
    # with per-position f32 absmax scales riding two extra refs. The
    # scale is constant over the contracted D axis, so it factors out
    # of both dots: scores scale by ks per kv COLUMN, and vs folds
    # into p before the pv dot. HBM reads the cache at half width.
    if quant:
        (ks_ref, vs_ref, o_ref, lse_ref,
         acc_ref, m_ref, l_ref) = rest
    else:
        ks_ref = vs_ref = None
        o_ref, lse_ref, acc_ref, m_ref, l_ref = rest
    iq = pl.program_id(2)
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_start = iq * bq
    k_start = ik * bk

    def _compute():
        # Keep operands in their native (bf16) dtype so the MXU runs at
        # full rate; accumulate f32 via preferred_element_type.
        q = q_ref[0, 0]                               # [bq, d]
        if quant:
            k = k_ref[0, 0].astype(q.dtype)
            v = v_ref[0, 0].astype(q.dtype)
        else:
            k = k_ref[0, 0]                           # [bk, d]
            v = v_ref[0, 0]                           # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk] f32
        if quant:
            s = s * ks_ref[0, 0][:, 0][None, :]       # per-column ks
        s, _ = _score_mods(s, q_start, k_start, w_ref, causal=causal,
                           windowed=windowed, softcap=softcap, bq=bq,
                           bk=bk)
        m_prev = m_ref[:, :1]                         # [bq, 1]
        m_blk = jnp.max(s, axis=1, keepdims=True)     # [bq, 1]
        m_new = jnp.maximum(m_prev, m_blk)
        safe_m = jnp.where(m_new <= _NEG_INF * 0.5, 0.0, m_new)
        p = jnp.exp(s - safe_m)                       # [bq, bk]
        correction = jnp.exp(m_prev - safe_m)         # [bq, 1]
        l_ref[:] = (l_ref[:] * correction +
                    jnp.sum(p, axis=1, keepdims=True))
        if quant:
            p = p * vs_ref[0, 0][:, 0][None, :]       # fold vs into p
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # [bq, d]
        acc_ref[:] = acc_ref[:] * correction + pv
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    visible = _block_visible(q_start, k_start, w_ref, causal=causal,
                             windowed=windowed, bq=bq, bk=bk)
    if visible is None:
        _compute()
    else:
        pl.when(visible)(_compute)

    @pl.when(ik == n_kv_blocks - 1)
    def _finalize():
        l = l_ref[:]
        norm = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] / norm).astype(o_ref.dtype)
        m = m_ref[:, :1]
        safe_m = jnp.where(m <= _NEG_INF * 0.5, 0.0, m)
        # Fully-masked rows (l == 0) get lse = +inf so backward's
        # exp(s - lse) is exactly 0 for them.
        lse = jnp.where(l > 0.0, safe_m + jnp.log(jnp.maximum(l, 1e-37)),
                        jnp.inf)
        lse_ref[0, 0] = lse


def _dq_kernel(w_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dq_ref, dq_acc, *, causal: bool, windowed: bool,
               softcap: Optional[float], scale: float, bq: int, bk: int,
               n_kv_blocks: int):
    iq = pl.program_id(2)
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    q_start = iq * bq
    k_start = ik * bk

    def _compute():
        q = q_ref[0, 0]                                # [bq, d]
        k = k_ref[0, 0]                                # [bk, d]
        v = v_ref[0, 0]                                # [bk, d]
        do = do_ref[0, 0]                              # [bq, d]
        lse = lse_ref[0, 0]                            # [bq, 1]
        delta = delta_ref[0, 0]                        # [bq, 1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        s, t = _score_mods(s, q_start, k_start, w_ref, causal=causal,
                           windowed=windowed, softcap=softcap, bq=bq,
                           bk=bk)
        p = jnp.exp(s - lse)                           # [bq, bk]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bq, bk]
        ds = p * (dp - delta)                          # [bq, bk]
        if t is not None:
            ds = ds * (1.0 - t * t)                    # softcap Jacobian
        ds = ds * scale
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bq, d]

    visible = _block_visible(q_start, k_start, w_ref, causal=causal,
                             windowed=windowed, bq=bq, bk=bk)
    if visible is None:
        _compute()
    else:
        pl.when(visible)(_compute)

    @pl.when(ik == n_kv_blocks - 1)
    def _store():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel(w_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc, *, causal: bool,
                windowed: bool, softcap: Optional[float], scale: float,
                bq: int, bk: int, n_q_blocks: int):
    ik = pl.program_id(2)
    iq = pl.program_id(3)

    @pl.when(iq == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_start = iq * bq
    k_start = ik * bk

    def _compute():
        q = q_ref[0, 0]                                # [bq, d]
        k = k_ref[0, 0]                                # [bk, d]
        v = v_ref[0, 0]                                # [bk, d]
        do = do_ref[0, 0]                              # [bq, d]
        lse = lse_ref[0, 0]                            # [bq, 1]
        delta = delta_ref[0, 0]                        # [bq, 1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        s, t = _score_mods(s, q_start, k_start, w_ref, causal=causal,
                           windowed=windowed, softcap=softcap, bq=bq,
                           bk=bk)
        p = jnp.exp(s - lse)                           # [bq, bk]
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bk, d]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bq, bk]
        ds = p * (dp - delta)                          # [bq, bk]
        if t is not None:
            ds = ds * (1.0 - t * t)                    # softcap Jacobian
        ds = ds * scale
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bk, d]

    # Visibility is symmetric in the block pair: reuse the same
    # predicate (the causal term reads "some query in the q tile can
    # see this kv tile").
    cond = None
    if causal:
        cond = w_ref[1] + q_start + bq > k_start
    if windowed:
        wc = k_start + bk + w_ref[0] > w_ref[1] + q_start + 1
        cond = wc if cond is None else cond & wc
    if cond is None:
        _compute()
    else:
        pl.when(cond)(_compute)

    @pl.when(iq == n_q_blocks - 1)
    def _store():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _blocks(s_q: int, s_kv: int, block_q: int, block_k: int):
    bq = min(block_q, s_q)
    bk = min(block_k, s_kv)
    if s_q % bq or s_kv % bk:
        raise ValueError(f'seq lens ({s_q},{s_kv}) must divide block '
                         f'sizes ({bq},{bk})')
    return bq, bk, s_q // bq, s_kv // bk


_SMEM_SPEC = pl.BlockSpec(memory_space=pltpu.SMEM)


def _clamped_kv_index(iq, ik, w_ref, *, bq: int, bk: int, n_k: int,
                      windowed: bool = True):
    """KV block index with masked steps pinned to a visible block.

    For q tile at GLOBAL rows [off + iq*bq, off + iq*bq + bq) the
    causally visible kv columns end at off + iq*bq + bq - 1, and under
    a sliding window start after off + iq*bq - w; grid steps outside
    that range re-fetch the boundary block instead of DMAing a tile
    the kernel will skip anyway (pallas elides the copy when the
    mapped index doesn't change). For windowed training that makes HBM
    traffic O(window) per q tile; for offset-causal cached prefill it
    means kv blocks past the causal frontier — most of a long cache on
    early chunks — are never read at all.
    """
    off = w_ref[1]
    hi = jnp.minimum((off + iq * bq + bq - 1) // bk, n_k - 1)
    lo = 0
    if windowed:
        lo = jnp.maximum((off + iq * bq - w_ref[0] + 1) // bk, 0)
    return jnp.clip(ik, lo, hi)


def _clamped_q_index(ik, iq, w_ref, *, bq: int, bk: int, n_q: int,
                     windowed: bool = True):
    """Mirror of _clamped_kv_index for the dkv grid (q innermost):
    visible q rows (global = local + off) for kv tile
    [ik*bk, ik*bk+bk) are [ik*bk, ik*bk + bk - 1 + w - 1]."""
    off = w_ref[1]
    lo = jnp.maximum((ik * bk - off) // bq, 0)
    hi = n_q - 1
    if windowed:
        hi = jnp.minimum((ik * bk + bk + w_ref[0] - 2 - off) // bq,
                         n_q - 1)
    return jnp.clip(iq, lo, hi)


def _flash_fwd_impl(q: jax.Array, k: jax.Array, v: jax.Array,
                    window: jax.Array, causal: bool, windowed: bool,
                    block_q: int, block_k: int,
                    softcap: Optional[float], interpret: bool,
                    offset_mode: bool = False,
                    k_scale: Optional[jax.Array] = None,
                    v_scale: Optional[jax.Array] = None):
    quant = k_scale is not None
    b, s_q, h, d = q.shape
    s_kv, h_kv = k.shape[1], k.shape[2]
    group = h // h_kv
    bq, bk, n_q, n_k = _blocks(s_q, s_kv, block_q, block_k)
    scale = 1.0 / math.sqrt(d)

    # [B,S,H,D] → [B,H,S,D]: the kernel tiles (seq, head_dim).
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    extra = []
    if quant:
        # Scales [B,S,KV] → [B,KV,S,1] so a (1,1,bk,1) block rides the
        # same kv index map as its int8 tensor.
        extra = [jnp.swapaxes(k_scale, 1, 2)[..., None],
                 jnp.swapaxes(v_scale, 1, 2)[..., None]]

    kernel = functools.partial(
        _fwd_kernel, causal=causal, windowed=windowed, softcap=softcap,
        scale=scale, bq=bq, bk=bk, n_kv_blocks=n_k, quant=quant)
    if causal and (windowed or offset_mode):
        # Scalar-prefetch grid: the window/offset scalars ride into the
        # INDEX MAPS, so fully-masked kv steps re-fetch the boundary
        # block (no new DMA) while pl.when skips their compute.
        def kv_map(b_, h_, iq, ik, w_ref):
            ik_c = _clamped_kv_index(iq, ik, w_ref, bq=bq, bk=bk,
                                     n_k=n_k, windowed=windowed)
            return (b_, h_ // group, ik_c, 0)

        in_specs = [
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, iq, ik, w:
                         (b_, h_, iq, 0)),
            pl.BlockSpec((1, 1, bk, d), kv_map),
            pl.BlockSpec((1, 1, bk, d), kv_map),
        ]
        if quant:
            in_specs += [pl.BlockSpec((1, 1, bk, 1), kv_map)] * 2
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, h, n_q, n_k),
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, 1, bq, d), lambda b_, h_, iq, ik, w:
                             (b_, h_, iq, 0)),
                pl.BlockSpec((1, 1, bq, 1), lambda b_, h_, iq, ik, w:
                             (b_, h_, iq, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((bq, d), jnp.float32),
                pltpu.VMEM((bq, 1), jnp.float32),
                pltpu.VMEM((bq, 1), jnp.float32),
            ],
        )
        out, lse = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=[
                jax.ShapeDtypeStruct((b, h, s_q, d), q.dtype),
                jax.ShapeDtypeStruct((b, h, s_q, 1), jnp.float32),
            ],
            interpret=interpret,
        )(window, qt, kt, vt, *extra)
        return jnp.swapaxes(out, 1, 2), lse
    in_specs = [
        _SMEM_SPEC,
        pl.BlockSpec((1, 1, bq, d), lambda b_, h_, iq, ik:
                     (b_, h_, iq, 0)),
        pl.BlockSpec((1, 1, bk, d), lambda b_, h_, iq, ik:
                     (b_, h_ // group, ik, 0)),
        pl.BlockSpec((1, 1, bk, d), lambda b_, h_, iq, ik:
                     (b_, h_ // group, ik, 0)),
    ]
    if quant:
        in_specs += [pl.BlockSpec((1, 1, bk, 1),
                                  lambda b_, h_, iq, ik:
                                  (b_, h_ // group, ik, 0))] * 2
    out, lse = pl.pallas_call(
        kernel,
        grid=(b, h, n_q, n_k),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, iq, ik:
                         (b_, h_, iq, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b_, h_, iq, ik:
                         (b_, h_, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s_q, d), q.dtype),
            # [B,H,Sq,1]: trailing singleton keeps TPU block tiling
            # legal ((bq, 1) is a valid last-two-dims block).
            jax.ShapeDtypeStruct((b, h, s_q, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
        ],
        interpret=interpret,
    )(window, qt, kt, vt, *extra)
    return jnp.swapaxes(out, 1, 2), lse


def _flash_bwd_impl(q, k, v, o, lse, do, window, causal, windowed,
                    block_q, block_k, softcap, interpret,
                    offset_mode=False):
    b, s_q, h, d = q.shape
    s_kv, h_kv = k.shape[1], k.shape[2]
    group = h // h_kv
    bq, bk, n_q, n_k = _blocks(s_q, s_kv, block_q, block_k)
    scale = 1.0 / math.sqrt(d)

    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    dot = jnp.swapaxes(do, 1, 2)
    # delta = rowsum(do * o): one cheap elementwise pass outside pallas.
    delta = jnp.sum(dot.astype(jnp.float32) *
                    jnp.swapaxes(o, 1, 2).astype(jnp.float32),
                    axis=-1, keepdims=True)            # [B,H,Sq,1] f32

    dq_kernel = functools.partial(
        _dq_kernel, causal=causal, windowed=windowed, softcap=softcap,
        scale=scale, bq=bq, bk=bk, n_kv_blocks=n_k)
    dkv_kernel = functools.partial(
        _dkv_kernel, causal=causal, windowed=windowed, softcap=softcap,
        scale=scale, bq=bq, bk=bk, n_q_blocks=n_q)

    if causal and (windowed or offset_mode):
        # Scalar-prefetch grids: masked steps re-fetch the boundary
        # block (see _clamped_kv_index) instead of DMAing skipped
        # tiles.
        def kv_map(b_, h_, iq, ik, w_ref):
            ik_c = _clamped_kv_index(iq, ik, w_ref, bq=bq, bk=bk,
                                     n_k=n_k, windowed=windowed)
            return (b_, h_ // group, ik_c, 0)

        q_specp = pl.BlockSpec((1, 1, bq, d), lambda b_, h_, iq, ik, w:
                               (b_, h_, iq, 0))
        row_specp = pl.BlockSpec((1, 1, bq, 1), lambda b_, h_, iq, ik,
                                 w: (b_, h_, iq, 0))
        kv_specp = pl.BlockSpec((1, 1, bk, d), kv_map)
        dqt = pl.pallas_call(
            dq_kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(b, h, n_q, n_k),
                in_specs=[q_specp, kv_specp, kv_specp, q_specp,
                          row_specp, row_specp],
                out_specs=q_specp,
                scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
            ),
            out_shape=jax.ShapeDtypeStruct((b, h, s_q, d), q.dtype),
            interpret=interpret,
        )(window, qt, kt, vt, dot, lse, delta)

        def q_map(b_, h_, ik, iq, w_ref):
            iq_c = _clamped_q_index(ik, iq, w_ref, bq=bq, bk=bk,
                                    n_q=n_q, windowed=windowed)
            return (b_, h_, iq_c, 0)

        def row_map(b_, h_, ik, iq, w_ref):
            iq_c = _clamped_q_index(ik, iq, w_ref, bq=bq, bk=bk,
                                    n_q=n_q, windowed=windowed)
            return (b_, h_, iq_c, 0)

        q_spec2p = pl.BlockSpec((1, 1, bq, d), q_map)
        row_spec2p = pl.BlockSpec((1, 1, bq, 1), row_map)
        kv_spec2p = pl.BlockSpec((1, 1, bk, d), lambda b_, h_, ik, iq,
                                 w: (b_, h_ // group, ik, 0))
        kv_out_specp = pl.BlockSpec((1, 1, bk, d), lambda b_, h_, ik,
                                    iq, w: (b_, h_, ik, 0))
        dkt_h, dvt_h = pl.pallas_call(
            dkv_kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(b, h, n_k, n_q),
                in_specs=[q_spec2p, kv_spec2p, kv_spec2p, q_spec2p,
                          row_spec2p, row_spec2p],
                out_specs=[kv_out_specp, kv_out_specp],
                scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                                pltpu.VMEM((bk, d), jnp.float32)],
            ),
            out_shape=[
                jax.ShapeDtypeStruct((b, h, s_kv, d), k.dtype),
                jax.ShapeDtypeStruct((b, h, s_kv, d), v.dtype),
            ],
            interpret=interpret,
        )(window, qt, kt, vt, dot, lse, delta)
        dq = jnp.swapaxes(dqt, 1, 2)
        if group > 1:
            dkt_h = dkt_h.reshape(b, h_kv, group, s_kv, d).sum(axis=2)
            dvt_h = dvt_h.reshape(b, h_kv, group, s_kv, d).sum(axis=2)
        dk = jnp.swapaxes(dkt_h, 1, 2).astype(k.dtype)
        dv = jnp.swapaxes(dvt_h, 1, 2).astype(v.dtype)
        return dq, dk, dv

    q_spec = pl.BlockSpec((1, 1, bq, d),
                          lambda b_, h_, iq, ik: (b_, h_, iq, 0))
    kv_spec = pl.BlockSpec((1, 1, bk, d),
                           lambda b_, h_, iq, ik: (b_, h_ // group, ik, 0))
    row_spec = pl.BlockSpec((1, 1, bq, 1),
                            lambda b_, h_, iq, ik: (b_, h_, iq, 0))

    dqt = pl.pallas_call(
        dq_kernel,
        grid=(b, h, n_q, n_k),
        in_specs=[_SMEM_SPEC, q_spec, kv_spec, kv_spec, q_spec, row_spec,
                  row_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, s_q, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(window, qt, kt, vt, dot, lse, delta)

    # dk/dv: kv-block major, q sequential innermost. Per-head partials;
    # GQA groups summed below.
    q_spec2 = pl.BlockSpec((1, 1, bq, d),
                           lambda b_, h_, ik, iq: (b_, h_, iq, 0))
    kv_spec2 = pl.BlockSpec((1, 1, bk, d),
                            lambda b_, h_, ik, iq: (b_, h_ // group, ik, 0))
    kv_out_spec = pl.BlockSpec((1, 1, bk, d),
                               lambda b_, h_, ik, iq: (b_, h_, ik, 0))
    row_spec2 = pl.BlockSpec((1, 1, bq, 1),
                             lambda b_, h_, ik, iq: (b_, h_, iq, 0))
    dkt_h, dvt_h = pl.pallas_call(
        dkv_kernel,
        grid=(b, h, n_k, n_q),
        in_specs=[_SMEM_SPEC, q_spec2, kv_spec2, kv_spec2, q_spec2,
                  row_spec2, row_spec2],
        out_specs=[kv_out_spec, kv_out_spec],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s_kv, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, s_kv, d), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        interpret=interpret,
    )(window, qt, kt, vt, dot, lse, delta)

    dq = jnp.swapaxes(dqt, 1, 2)
    if group > 1:
        dkt_h = dkt_h.reshape(b, h_kv, group, s_kv, d).sum(axis=2)
        dvt_h = dvt_h.reshape(b, h_kv, group, s_kv, d).sum(axis=2)
    dk = jnp.swapaxes(dkt_h, 1, 2).astype(k.dtype)
    dv = jnp.swapaxes(dvt_h, 1, 2).astype(v.dtype)
    return dq, dk, dv


def _use_interpret() -> bool:
    return jax.default_backend() != 'tpu'


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _flash(q, k, v, window, causal, windowed, block_q, block_k, softcap,
           offset_mode):
    out, _ = _flash_fwd_impl(q, k, v, window, causal, windowed, block_q,
                             block_k, softcap, interpret=_use_interpret(),
                             offset_mode=offset_mode)
    return out


def _fwd(q, k, v, window, causal, windowed, block_q, block_k, softcap,
         offset_mode):
    out, lse = _flash_fwd_impl(q, k, v, window, causal, windowed,
                               block_q, block_k, softcap,
                               interpret=_use_interpret(),
                               offset_mode=offset_mode)
    return out, (q, k, v, window, out, lse)


def _bwd(causal, windowed, block_q, block_k, softcap, offset_mode, res,
         g):
    q, k, v, window, o, lse = res
    dq, dk, dv = _flash_bwd_impl(q, k, v, o, lse, g, window, causal,
                                 windowed, block_q, block_k, softcap,
                                 interpret=_use_interpret(),
                                 offset_mode=offset_mode)
    # int32 scalars take a float0 cotangent (no gradient flows to them).
    return dq, dk, dv, np.zeros((2,), dtype=jax.dtypes.float0)


_flash.defvjp(_fwd, _bwd)


def flash_attention_quant(q: jax.Array, k_q: jax.Array,
                          k_scale: jax.Array, v_q: jax.Array,
                          v_scale: jax.Array,
                          causal: bool = True, block_q: int = 512,
                          block_k: int = 512,
                          window: Optional[jax.Array] = None,
                          softcap: Optional[float] = None,
                          q_offset: Optional[jax.Array] = None
                          ) -> jax.Array:
    """Flash attention over an int8 KV cache (engine.quantize_kv
    layout): k_q/v_q [B,Skv,Hkv,D] int8, scales [B,Skv,Hkv] f32.

    Forward-only (serving prefill — training keeps bf16 caches): the
    per-position scale factors out of the contracted D axis, so the
    kernel reads the cache at half the HBM width, dequantizes in
    VMEM, and applies ks to the score columns / folds vs into p. The
    q_offset / window / softcap machinery (incl. DMA-level skipping of
    blocks past the causal frontier) is shared with flash_attention —
    this is what lets long-context chunked prefill compose with the
    int8 cache instead of falling back to dense O(S)-per-chunk reads.
    """
    if window is not None and not causal:
        raise ValueError('flash window support is causal-only')
    if q_offset is not None and not causal:
        raise ValueError('q_offset requires causal masking')
    windowed = window is not None
    offset_mode = q_offset is not None
    scalars = jnp.stack([
        jnp.asarray(window if windowed else 0, jnp.int32).reshape(()),
        jnp.asarray(q_offset if offset_mode else 0,
                    jnp.int32).reshape(()),
    ])
    out, _ = _flash_fwd_impl(
        q, k_q, v_q, scalars, causal, windowed, block_q, block_k,
        None if softcap is None else float(softcap),
        interpret=_use_interpret(), offset_mode=offset_mode,
        k_scale=k_scale, v_scale=v_scale)
    return out


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True, block_q: int = 512,
                    block_k: int = 512,
                    window: Optional[jax.Array] = None,
                    softcap: Optional[float] = None,
                    q_offset: Optional[jax.Array] = None) -> jax.Array:
    """Flash attention. q:[B,Sq,H,D], k/v:[B,Skv,Hkv,D] → [B,Sq,H,D].

    window: sliding-window size — position q attends k iff
    q_pos - k_pos < window. May be a traced int32 scalar (the model
    stacks scan per-layer windows through one compiled body); requires
    causal. softcap: static Gemma-style logit cap, cap·tanh(s/cap).

    q_offset (traced int32 scalar, requires causal): global position of
    q row 0 — rectangular cached-prefill attention where a [B,T] chunk
    starting at cache position `q_offset` attends a [B,S_kv] KV cache.
    Row t masks as global position q_offset + t, and kv blocks past
    the causal frontier are skipped at the DMA level (the chunked
    long-context prefill cost is O(frontier), not O(S_kv)).
    """
    if window is not None and not causal:
        raise ValueError('flash window support is causal-only; use '
                         'blockwise for non-causal windows')
    if q_offset is not None and not causal:
        raise ValueError('q_offset (cached-prefill attention) requires '
                         'causal masking')
    windowed = window is not None
    offset_mode = q_offset is not None
    scalars = jnp.stack([
        jnp.asarray(window if windowed else 0, jnp.int32).reshape(()),
        jnp.asarray(q_offset if offset_mode else 0,
                    jnp.int32).reshape(()),
    ])
    return _flash(q, k, v, scalars, causal, windowed, block_q, block_k,
                  None if softcap is None else float(softcap),
                  offset_mode)
