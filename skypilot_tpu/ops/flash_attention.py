"""Fused flash attention as a Pallas TPU kernel.

Forward pass is a hand-written kernel: grid over (batch, head, q-block,
kv-block), online-softmax accumulators live in VMEM scratch that
persists across the sequential innermost grid dimension (TPU grids are
sequential, so the kv loop accumulates in-place), and the [bq, bk] score
tile never leaves VMEM — HBM traffic is O(S·D) instead of O(S²).

Backward uses a custom VJP that recomputes attention blockwise — flash
memory behavior (no stored probs) at the cost of one recompute, matching
`jax.checkpoint` economics. A dedicated backward kernel is a later
optimization.

GQA is folded into the index maps: kv blocks for head h come from kv
head h // (num_heads // num_kv_heads), so no materialized repeat.

No reference equivalent (SkyPilot ships no kernels; SURVEY.md §2.11).
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                causal: bool, scale: float, bq: int, bk: int,
                n_kv_blocks: int):
    iq = pl.program_id(2)
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_start = iq * bq
    k_start = ik * bk

    def _compute():
        # Keep operands in their native (bf16) dtype so the MXU runs at
        # full rate; accumulate f32 via preferred_element_type.
        q = q_ref[0, 0]                               # [bq, d]
        k = k_ref[0, 0]                               # [bk, d]
        v = v_ref[0, 0]                               # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk] f32
        if causal:
            q_pos = q_start + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            k_pos = k_start + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_prev = m_ref[:, :1]                         # [bq, 1]
        m_blk = jnp.max(s, axis=1, keepdims=True)     # [bq, 1]
        m_new = jnp.maximum(m_prev, m_blk)
        safe_m = jnp.where(m_new <= _NEG_INF * 0.5, 0.0, m_new)
        p = jnp.exp(s - safe_m)                       # [bq, bk]
        correction = jnp.exp(m_prev - safe_m)         # [bq, 1]
        l_ref[:] = (l_ref[:] * correction +
                    jnp.sum(p, axis=1, keepdims=True))
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # [bq, d]
        acc_ref[:] = acc_ref[:] * correction + pv
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    if causal:
        # Skip kv blocks strictly above the causal diagonal.
        pl.when(k_start < q_start + bq)(_compute)
    else:
        _compute()

    @pl.when(ik == n_kv_blocks - 1)
    def _finalize():
        norm = l_ref[:]
        norm = jnp.where(norm == 0.0, 1.0, norm)
        o_ref[0, 0] = (acc_ref[:] / norm).astype(o_ref.dtype)


def _flash_fwd_impl(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool, block_q: int, block_k: int,
                    interpret: bool) -> jax.Array:
    b, s_q, h, d = q.shape
    s_kv, h_kv = k.shape[1], k.shape[2]
    group = h // h_kv
    bq = min(block_q, s_q)
    bk = min(block_k, s_kv)
    if s_q % bq or s_kv % bk:
        raise ValueError(f'seq lens ({s_q},{s_kv}) must divide block '
                         f'sizes ({bq},{bk})')
    n_q, n_k = s_q // bq, s_kv // bk
    scale = 1.0 / math.sqrt(d)

    # [B,S,H,D] → [B,H,S,D]: the kernel tiles (seq, head_dim).
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)

    kernel = functools.partial(
        _fwd_kernel, causal=causal, scale=scale, bq=bq, bk=bk,
        n_kv_blocks=n_k)
    out = pl.pallas_call(
        kernel,
        grid=(b, h, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, iq, ik:
                         (b_, h_, iq, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, iq, ik:
                         (b_, h_ // group, ik, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, iq, ik:
                         (b_, h_ // group, ik, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, d), lambda b_, h_, iq, ik:
                               (b_, h_, iq, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, s_q, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt)
    return jnp.swapaxes(out, 1, 2)


def _use_interpret() -> bool:
    return jax.default_backend() != 'tpu'


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True, block_q: int = 512,
                    block_k: int = 512) -> jax.Array:
    """Flash attention. q:[B,Sq,H,D], k/v:[B,Skv,Hkv,D] → [B,Sq,H,D]."""
    return _flash_fwd_impl(q, k, v, causal, block_q, block_k,
                           interpret=_use_interpret())


def _fwd(q, k, v, causal, block_q, block_k):
    out = _flash_fwd_impl(q, k, v, causal, block_q, block_k,
                          interpret=_use_interpret())
    return out, (q, k, v)


def _bwd(causal, block_q, block_k, res, g):
    from skypilot_tpu.ops import attention as attention_ops
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: attention_ops.blockwise_attention(
            q_, k_, v_, causal=causal, block_size=block_k), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
