"""Attention ops: dense, blockwise (online softmax), and ring attention.

The reference has NO sequence-length scaling machinery — long context is
reached only through user recipes (SURVEY.md §2.11). Here it is a core
op: `ring_attention` shards the sequence over the mesh's `context` axis
and rotates KV blocks around the ring with `lax.ppermute`, overlapping
ICI transfers with the per-block attention compute that XLA schedules on
the MXU. All variants use the same online-softmax accumulator, so the
ring result is bitwise-comparable to dense attention up to reduction
order.

Shapes (query-grouped attention throughout):
  q: [batch, q_len, num_heads, head_dim]
  k,v: [batch, kv_len, num_kv_heads, head_dim]
Output: [batch, q_len, num_heads, head_dim]
"""
from __future__ import annotations

import functools
import math
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


def _repeat_kv(kv: jax.Array, num_heads: int) -> jax.Array:
    """[B,S,Hkv,D] → [B,S,H,D] by repeating each kv head H/Hkv times."""
    b, s, hkv, d = kv.shape
    if hkv == num_heads:
        return kv
    reps = num_heads // hkv
    kv = jnp.broadcast_to(kv[:, :, :, None, :], (b, s, hkv, reps, d))
    return kv.reshape(b, s, num_heads, d)


def dense_attention(q: jax.Array,
                    k: jax.Array,
                    v: jax.Array,
                    causal: bool = True,
                    q_offset: int = 0,
                    kv_offset: int = 0,
                    window: Optional[Any] = None,
                    softcap: Optional[float] = None) -> jax.Array:
    """Plain softmax attention; the correctness reference for the rest.

    q_offset/kv_offset are the global positions of element 0 — needed
    when sequence is sharded and this rank sees only a slice.
    window: sliding-window size (Mistral/Gemma local layers) — position
    q attends k iff q_pos - k_pos < window; may be a traced scalar so
    alternating local/global layers stay inside one lax.scan. softcap:
    Gemma-style attn-logit soft-capping, cap*tanh(scores/cap).
    """
    num_heads = q.shape[2]
    k = _repeat_kv(k, num_heads)
    v = _repeat_kv(v, num_heads)
    scale = 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum('bqhd,bkhd->bhqk', q, k,
                        preferred_element_type=jnp.float32) * scale
    if softcap is not None:
        scores = softcap * jnp.tanh(scores / softcap)
    q_pos = q_offset + jnp.arange(q.shape[1])
    k_pos = kv_offset + jnp.arange(k.shape[1])
    if causal:
        mask = q_pos[:, None] >= k_pos[None, :]
        if window is not None:
            mask = mask & (q_pos[:, None] - k_pos[None, :] < window)
        scores = jnp.where(mask[None, None], scores, _NEG_INF)
    elif window is not None:
        mask = jnp.abs(q_pos[:, None] - k_pos[None, :]) < window
        scores = jnp.where(mask[None, None], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    return jnp.einsum('bhqk,bkhd->bqhd', probs, v)


def _block_update(q, k, v, scores_mask, acc_o, acc_m, acc_l,
                  softcap=None):
    """One online-softmax step: fold a KV block into the accumulators.

    acc_o: [B,Q,H,D] f32 weighted values; acc_m/acc_l: [B,H,Q] f32
    running max / normalizer.
    """
    scale = 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum('bqhd,bkhd->bhqk', q, k,
                        preferred_element_type=jnp.float32) * scale
    if softcap is not None:
        scores = softcap * jnp.tanh(scores / softcap)
    if scores_mask is not None:
        scores = jnp.where(scores_mask, scores, _NEG_INF)
    block_max = jnp.max(scores, axis=-1)
    new_m = jnp.maximum(acc_m, block_max)
    # safe_m: when every key seen so far is masked, new_m is still
    # _NEG_INF; subtracting it would turn exp(-inf - -inf) into 1s.
    # Shift by 0 instead so fully-masked rows keep probs == 0.
    safe_m = jnp.where(new_m <= _NEG_INF * 0.5, 0.0, new_m)
    probs = jnp.exp(scores - safe_m[..., None])
    correction = jnp.exp(acc_m - safe_m)
    new_l = acc_l * correction + jnp.sum(probs, axis=-1)
    pv = jnp.einsum('bhqk,bkhd->bqhd', probs.astype(v.dtype), v,
                    preferred_element_type=jnp.float32)
    new_o = acc_o * jnp.transpose(correction, (0, 2, 1))[..., None] + pv
    return new_o, new_m, new_l


def _finalize(acc_o, acc_m, acc_l, dtype):
    norm = jnp.transpose(acc_l, (0, 2, 1))[..., None]
    norm = jnp.where(norm == 0.0, 1.0, norm)
    return (acc_o / norm).astype(dtype)


def blockwise_attention(q: jax.Array,
                        k: jax.Array,
                        v: jax.Array,
                        causal: bool = True,
                        block_size: int = 512,
                        q_offset: int = 0,
                        kv_offset: int = 0,
                        window: Optional[Any] = None,
                        softcap: Optional[float] = None) -> jax.Array:
    """Memory-efficient attention: scan over KV blocks, never
    materializing the full [Q,K] score matrix. O(S) memory in sequence.

    window/softcap: sliding-window mask and Gemma logit soft-capping
    (see dense_attention).
    """
    b, q_len, num_heads, d = q.shape
    kv_len = k.shape[1]
    k = _repeat_kv(k, num_heads)
    v = _repeat_kv(v, num_heads)
    block_size = min(block_size, kv_len)
    num_blocks = -(-kv_len // block_size)
    pad = num_blocks * block_size - kv_len
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    k_blocks = k.reshape(b, num_blocks, block_size, num_heads, d)
    v_blocks = v.reshape(b, num_blocks, block_size, num_heads, d)

    q_pos = q_offset + jnp.arange(q_len)

    def body(carry, blk):
        acc_o, acc_m, acc_l = carry
        blk_idx, k_blk, v_blk = blk
        k_pos = kv_offset + blk_idx * block_size + jnp.arange(block_size)
        mask = k_pos[None, :] < kv_offset + kv_len  # padding mask
        if causal:
            mask = mask & (q_pos[:, None] >= k_pos[None, :])
        else:
            mask = jnp.broadcast_to(mask, (q_len, block_size))
        if window is not None:
            mask = mask & (q_pos[:, None] - k_pos[None, :] < window)
            if not causal:
                mask = mask & (k_pos[None, :] - q_pos[:, None] < window)
        carry = _block_update(q, k_blk, v_blk, mask[None, None], acc_o,
                              acc_m, acc_l, softcap=softcap)
        return carry, None

    acc = (jnp.zeros((b, q_len, num_heads, d), jnp.float32),
           jnp.full((b, num_heads, q_len), _NEG_INF, jnp.float32),
           jnp.zeros((b, num_heads, q_len), jnp.float32))
    xs = (jnp.arange(num_blocks),
          jnp.moveaxis(k_blocks, 1, 0), jnp.moveaxis(v_blocks, 1, 0))
    (acc_o, acc_m, acc_l), _ = lax.scan(body, acc, xs)
    return _finalize(acc_o, acc_m, acc_l, q.dtype)


def ring_attention(q: jax.Array,
                   k: jax.Array,
                   v: jax.Array,
                   mesh: Any,
                   axis: str = 'context',
                   causal: bool = True,
                   block_size: int = 512) -> jax.Array:
    """Ring attention over the mesh's sequence-parallel axis.

    Inputs are GLOBAL arrays whose seq dim is (or will be) sharded over
    `axis`; inside shard_map each rank holds one contiguous slice.
    Every step each rank attends q_local × kv_block then ppermutes the
    KV block (and its global offset) to the next rank — after
    ring_size steps every rank has seen the full sequence. Transfers are
    neighbor-only, so they ride ICI at full bandwidth.

    Design follows the public blockwise/ring-attention formulation
    (Liu et al.; see PAPERS.md) — not the reference, which has no such
    machinery (SURVEY.md §2.11: 'Not implemented anywhere in-tree').
    """
    from jax.sharding import PartitionSpec as P

    ring_size = mesh.shape[axis]
    if ring_size == 1:
        return blockwise_attention(q, k, v, causal=causal,
                                   block_size=block_size)
    seq_len = q.shape[1]
    if seq_len % ring_size:
        raise ValueError(f'seq_len {seq_len} % ring {ring_size} != 0')
    local_len = seq_len // ring_size
    # Sub-block each ring step so per-step score matrices stay
    # [local_len, sub_len] regardless of chunk size.
    sub_len = block_size if local_len % block_size == 0 else local_len
    n_sub = local_len // sub_len

    # Partition batch over the data axes and heads over tensor, matching
    # DEFAULT_RULES — otherwise shard_map would gather the full global
    # batch onto every rank. Fall back to replication per-dim when the
    # (static) shape doesn't divide the mesh axes (small test inputs).
    batch_axes = tuple(a for a in ('data', 'fsdp') if a in mesh.shape)
    batch_div = math.prod(mesh.shape[a] for a in batch_axes) or 1
    if q.shape[0] % batch_div:
        batch_axes = ()
    head_axis = 'tensor' if 'tensor' in mesh.shape else None
    if head_axis and (q.shape[2] % mesh.shape[head_axis]
                      or k.shape[2] % mesh.shape[head_axis]):
        head_axis = None
    qspec = P(batch_axes or None, axis, head_axis, None)

    def local_fn(q_loc, k_loc, v_loc):
        my_idx = lax.axis_index(axis)
        q_off = my_idx * local_len
        b, _, num_heads, d = q_loc.shape
        q_pos = q_off + jnp.arange(local_len)

        def fold_chunk(acc_o, acc_m, acc_l, k_blk, v_blk, kv_off):
            """Online-softmax the whole received chunk, sub-block at a
            time (inner scan keeps memory at [local_len, sub_len])."""
            k_sub = k_blk.reshape(b, n_sub, sub_len, *k_blk.shape[2:])
            v_sub = v_blk.reshape(b, n_sub, sub_len, *v_blk.shape[2:])

            def sub_body(carry, idx_kv):
                acc_o, acc_m, acc_l = carry
                s_idx, k_s, v_s = idx_kv
                k_pos = kv_off + s_idx * sub_len + jnp.arange(sub_len)
                if causal:
                    mask = (q_pos[:, None] >= k_pos[None, :])[None, None]
                else:
                    mask = None
                # Repeat GQA heads only for local compute; the ring
                # carries compact kv so ICI traffic stays kv-sized.
                carry = _block_update(
                    q_loc, _repeat_kv(k_s, num_heads),
                    _repeat_kv(v_s, num_heads), mask, acc_o, acc_m,
                    acc_l)
                return carry, None

            xs = (jnp.arange(n_sub), jnp.moveaxis(k_sub, 1, 0),
                  jnp.moveaxis(v_sub, 1, 0))
            (acc_o, acc_m, acc_l), _ = lax.scan(
                sub_body, (acc_o, acc_m, acc_l), xs)
            return acc_o, acc_m, acc_l

        def body(carry, _):
            acc_o, acc_m, acc_l, k_blk, v_blk, blk_idx = carry
            # Masking uses GLOBAL positions (f32 accumulators keep the
            # softmax exact across the ring).
            acc_o, acc_m, acc_l = fold_chunk(
                acc_o, acc_m, acc_l, k_blk, v_blk, blk_idx * local_len)
            perm = [(i, (i + 1) % ring_size) for i in range(ring_size)]
            k_blk = lax.ppermute(k_blk, axis, perm)
            v_blk = lax.ppermute(v_blk, axis, perm)
            blk_idx = lax.ppermute(blk_idx, axis, perm)
            return (acc_o, acc_m, acc_l, k_blk, v_blk, blk_idx), None

        # pvary: mark the zero-init accumulators as device-varying over
        # every mesh axis the inputs vary over, so scan's carry typing
        # matches (jax>=0.7 varying-manual-axes).
        vary = tuple(a for a in (*batch_axes, axis, head_axis) if a)
        acc = (lax.pvary(jnp.zeros((b, local_len, num_heads, d),
                                   jnp.float32), vary),
               lax.pvary(jnp.full((b, num_heads, local_len), _NEG_INF,
                                  jnp.float32), vary),
               lax.pvary(jnp.zeros((b, num_heads, local_len),
                                   jnp.float32), vary),
               k_loc, v_loc, my_idx)
        (acc_o, acc_m, acc_l, *_), _ = lax.scan(
            body, acc, None, length=ring_size)
        return _finalize(acc_o, acc_m, acc_l, q_loc.dtype)

    shard_mapped = jax.shard_map(
        local_fn, mesh=mesh,
        in_specs=(qspec, qspec, qspec),
        out_specs=qspec)
    return shard_mapped(q, k, v)


def attention(q: jax.Array,
              k: jax.Array,
              v: jax.Array,
              causal: bool = True,
              impl: str = 'dense',
              mesh: Optional[Any] = None,
              block_size: int = 512,
              window: Optional[Any] = None,
              softcap: Optional[float] = None) -> jax.Array:
    """Dispatch: 'dense' | 'blockwise' | 'ring' | 'flash' (TPU pallas).

    window/softcap (sliding-window local attention, Gemma logit
    capping) run in-kernel on the flash path (window may be a traced
    per-layer scalar); the only flash fallback is a non-causal window,
    which goes to blockwise. ring rejects them (a window never spans
    the context shards ring targets).
    """
    if impl == 'ring':
        if mesh is None:
            raise ValueError('ring attention requires a mesh')
        if window is not None or softcap is not None:
            raise ValueError('ring attention does not support '
                             'window/softcap; use blockwise')
        return ring_attention(q, k, v, mesh, causal=causal,
                              block_size=block_size)
    if impl == 'blockwise' or (impl == 'flash' and window is not None
                               and not causal):
        return blockwise_attention(q, k, v, causal=causal,
                                   block_size=block_size,
                                   window=window, softcap=softcap)
    if impl == 'flash':
        from skypilot_tpu.ops import flash_attention as fa
        return fa.flash_attention(q, k, v, causal, block_size,
                                  block_size, window=window,
                                  softcap=softcap)
    if impl == 'dense':
        return dense_attention(q, k, v, causal=causal, window=window,
                               softcap=softcap)
    raise ValueError(
        f'Unknown attention impl {impl!r}; '
        "expected 'dense' | 'blockwise' | 'ring' | 'flash'")
