"""Usage telemetry: command events spooled locally, shipped if configured.

Reference analog: sky/usage/usage_lib.py:42 (messages to a Grafana Loki
endpoint; heartbeats via skylet events). Ours: every recorded event is
appended to a local JSONL spool (always — it doubles as an audit log);
when SKYTPU_USAGE_ENDPOINT is set, events POST there best-effort.
Disable entirely with SKYTPU_DISABLE_USAGE_COLLECTION=1.
"""
import contextlib
import json
import os
import threading
import time
import urllib.request
from typing import Any, Dict, Optional

from skypilot_tpu import envs
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import paths

_lock = threading.Lock()


def _after_fork_in_child() -> None:
    """Fresh lock in forked children (parent is multi-threaded)."""
    global _lock
    _lock = threading.Lock()


os.register_at_fork(after_in_child=_after_fork_in_child)


def disabled() -> bool:
    raw = envs.SKYTPU_DISABLE_USAGE_COLLECTION.raw()
    if not raw:
        return False
    # Fail-safe for a privacy flag: ANY non-empty value except an
    # explicit '0'/'false' disables (the pre-registry contract) — an
    # operator's SKYTPU_DISABLE_USAGE_COLLECTION=off must not silently
    # re-enable telemetry under the registry's stricter bool parse.
    return raw.strip().lower() not in ('0', 'false')


def spool_path() -> str:
    return os.path.join(paths.state_dir(), 'usage_events.jsonl')


def _max_spool_bytes() -> int:
    """The spool doubles as an audit log but must not grow unboundedly
    on a long-lived API server: at the cap it rotates to ONE .1
    generation (append-heavy workloads lose at most the oldest half of
    history). Read at call time through the registry — a malformed
    knob falls back to the default instead of taking down imports."""
    return envs.SKYTPU_USAGE_SPOOL_MAX_BYTES.get()


def _rotate_locked(path: str) -> None:
    """Caller holds `_lock`. Rotate spool -> spool.1 when over cap."""
    try:
        if os.path.getsize(path) < _max_spool_bytes():
            return
    except OSError:
        return
    try:
        os.replace(path, path + '.1')
    except OSError:
        pass


def record_event(event_name: str, **fields: Any
                 ) -> Optional[Dict[str, Any]]:
    """Append one event; ship best-effort if an endpoint is set."""
    if disabled():
        return None
    event = {
        'event': event_name,
        'time': time.time(),
        'user': common_utils.get_user_hash(),
        'run_id': common_utils.get_usage_run_id(),
        **fields,
    }
    with _lock:
        _rotate_locked(spool_path())
        with open(spool_path(), 'a', encoding='utf-8') as f:
            f.write(json.dumps(event) + '\n')
    endpoint = envs.SKYTPU_USAGE_ENDPOINT.get()
    if endpoint:
        # Ship from a daemon thread: callers may be on the API server's
        # event loop, and a slow endpoint must cost them nothing.
        threading.Thread(target=_post, args=(endpoint, event),
                         daemon=True).start()
    return event


def _post(endpoint: str, event: Dict[str, Any]) -> None:
    try:
        req = urllib.request.Request(
            endpoint, data=json.dumps(event).encode(),
            headers={'Content-Type': 'application/json'},
            method='POST')
        with urllib.request.urlopen(req, timeout=5):
            pass
    except Exception:  # noqa: BLE001 — telemetry must never break UX
        pass


@contextlib.contextmanager
def timed_event(event_name: str, **fields: Any):
    """Record <name>.start/.done(+duration)/.failed around a block."""
    start = time.time()
    record_event(f'{event_name}.start', **fields)
    try:
        yield
    except BaseException as e:
        record_event(f'{event_name}.failed', duration_s=time.time() - start,
                     error=type(e).__name__, **fields)
        raise
    record_event(f'{event_name}.done', duration_s=time.time() - start,
                 **fields)
