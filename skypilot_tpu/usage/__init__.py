"""Usage telemetry (opt-out, local-spool + optional endpoint)."""
