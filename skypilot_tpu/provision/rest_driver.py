"""Shared lifecycle driver for REST-API VM clouds.

Eight neoclouds (lambda/runpod/nebius/do/fluidstack/vast/cudo/
paperspace) speak the same lifecycle dialect — list instances, map a
cloud status word onto {pending,running,stopping,stopped,terminated},
launch `<cluster>-<i>`-named nodes skipping the live ones, resume the
stopped ones, refuse relaunch over a dying twin, poll until running,
classify API errors into the failover taxonomy. Only the endpoints,
payloads and field names differ. This module owns the dialect ONCE;
each cloud contributes a declarative `RestVmSpec` (status map, create
payload, host-address extraction, optional key/project setup).

Reference analog: each of sky/provision/{lambda_cloud,runpod,do,
fluidstack,vast,cudo,paperspace}/instance.py re-implements this loop
per cloud (400-900 LoC each); factoring it is the TPU-repo design
choice, not a translation.

Driver-wide guarantees (each previously hand-rolled per cloud, with
drift — e.g. nebius would relaunch over a 'stopping' twin, vast
refused relaunch over a terminated leftover):
- duplicate-name safety: liveness is judged across ALL same-name
  instances, never last-listed-wins;
- a name whose only live record is 'stopping' refuses relaunch
  (`common.refuse_unresumable`);
- terminated leftovers never block relaunch;
- stop on a stop-incapable cloud raises NotSupportedError;
- every REST error is re-raised through the cloud's
  `classify_api_error` so capacity/auth failures hit the failover
  engine with the right taxonomy.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.provision import common


@dataclasses.dataclass
class Ctx:
    """Per-operation context handed to every spec callback."""
    cluster: str
    region: Optional[str]
    provider_config: Dict[str, Any]
    # provider_config ∪ node_config (launch ops only).
    nc: Dict[str, Any] = dataclasses.field(default_factory=dict)
    config: Optional[common.ProvisionConfig] = None
    # prepare_context/prepare_launch outputs (project id, key name, …).
    data: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class RestVmSpec:
    """What a REST cloud must declare; everything else is the driver."""
    provider: str
    adaptor: Any                 # client() + RestApiError + classify_api_error
    ssh_user: str
    # (client, ctx) -> instances of THIS cluster (exact-name matched).
    list_instances: Callable[[Any, Ctx], List[Dict[str, Any]]]
    # instance -> canonical state word.
    state: Callable[[Dict[str, Any]], str]
    # instance -> its `<cluster>-<i>` name.
    name_of: Callable[[Dict[str, Any]], str]
    # (client, ctx, name): POST the create call for one node.
    create: Callable[[Any, Ctx, str], None]
    # instance -> HostInfo (address/port extraction).
    host_info: Callable[[Dict[str, Any]], common.HostInfo]
    # Per-instance teardown; or terminate_all for bulk APIs.
    terminate: Optional[Callable[[Any, Ctx, Dict[str, Any]], None]] = None
    terminate_all: Optional[Callable[[Any, Ctx], None]] = None
    # Omitted => the cloud cannot stop/resume (NotSupportedError).
    stop: Optional[Callable[[Any, Ctx, Dict[str, Any]], None]] = None
    resume: Optional[Callable[[Any, Ctx, Dict[str, Any]], None]] = None
    # Runs for EVERY operation (cheap context: project resolution).
    prepare_context: Optional[Callable[[Any, Ctx], None]] = None
    # Runs before launching only (SSH key registration, …).
    prepare_launch: Optional[Callable[[Any, Ctx], None]] = None
    # Terminate instances in these states ('terminated' is skipped
    # unless listed — deleting a gone instance 404s on most clouds).
    terminate_terminated: bool = False


class RestVmDriver:
    """Binds a RestVmSpec to the uniform provisioner interface; cloud
    modules re-export the bound methods as their module functions."""

    def __init__(self, spec: RestVmSpec):
        self.spec = spec

    # -- helpers -------------------------------------------------------------

    def _ctx(self, cluster: str, region: Optional[str],
             provider_config: Dict[str, Any],
             config: Optional[common.ProvisionConfig] = None) -> Ctx:
        nc: Dict[str, Any] = {}
        if config is not None:
            nc = {**config.provider_config, **config.node_config}
        return Ctx(cluster=cluster, region=region,
                   provider_config=provider_config, nc=nc, config=config)

    def _classified(self, fn):
        try:
            return fn()
        except self.spec.adaptor.RestApiError as e:
            raise self.spec.adaptor.classify_api_error(e) from e

    # -- uniform interface ---------------------------------------------------

    def run_instances(self, region: str, cluster_name_on_cloud: str,
                      config: common.ProvisionConfig
                      ) -> common.ProvisionRecord:
        spec = self.spec
        client = spec.adaptor.client()
        ctx = self._ctx(cluster_name_on_cloud, region,
                        config.provider_config, config)
        created: List[str] = []
        resumed: List[str] = []

        def _launch():
            if spec.prepare_context:
                spec.prepare_context(client, ctx)
            if spec.prepare_launch:
                spec.prepare_launch(client, ctx)
            existing = spec.list_instances(client, ctx)
            # Classify per NAME over all same-name instances: a
            # terminating twin can coexist with its live replacement,
            # and liveness must win over last-listed order.
            alive, stopping = set(), set()
            stopped: Dict[str, Dict[str, Any]] = {}
            for inst in existing:
                name, state = spec.name_of(inst), spec.state(inst)
                if state in ('running', 'pending'):
                    alive.add(name)
                elif state == 'stopped':
                    stopped.setdefault(name, inst)
                elif state == 'stopping':
                    stopping.add(name)
            stopping -= alive

            for i in range(config.count):
                name = f'{cluster_name_on_cloud}-{i}'
                if name in alive:
                    continue
                if name in stopped:
                    if not config.resume_stopped_nodes:
                        raise exceptions.ProvisionError(
                            f'Instance {name} is stopped; pass '
                            'resume_stopped_nodes to restart it.')
                    if spec.resume is None:
                        raise exceptions.NotSupportedError(
                            f'{spec.provider} cannot resume stopped '
                            f'instance {name}.')
                    spec.resume(client, ctx, stopped[name])
                    resumed.append(name)
                    continue
                if name in stopping:
                    common.refuse_unresumable('stopping', name)
                spec.create(client, ctx, name)
                created.append(name)
            common.wait_until_running(
                lambda: spec.list_instances(client, ctx),
                config.count, spec.state, spec.name_of,
                timeout=float(config.provider_config.get(
                    'provision_timeout', 900)))

        self._classified(_launch)
        return common.ProvisionRecord(
            provider_name=spec.provider, region=region, zone=None,
            cluster_name_on_cloud=cluster_name_on_cloud,
            head_instance_id=f'{cluster_name_on_cloud}-0',
            created_instance_ids=created, resumed_instance_ids=resumed)

    def wait_instances(self, region: str, cluster_name_on_cloud: str,
                       state: Optional[str] = None) -> None:
        del region, cluster_name_on_cloud, state  # run_instances waits

    def stop_instances(self, cluster_name_on_cloud: str,
                       provider_config: Dict[str, Any]) -> None:
        spec = self.spec
        if spec.stop is None:
            raise exceptions.NotSupportedError(
                f'{spec.provider} cannot stop instances; use terminate '
                '(down).')
        client = spec.adaptor.client()
        ctx = self._ctx(cluster_name_on_cloud, None, provider_config)

        def _stop():
            if spec.prepare_context:
                spec.prepare_context(client, ctx)
            for inst in spec.list_instances(client, ctx):
                if spec.state(inst) == 'running':
                    spec.stop(client, ctx, inst)

        self._classified(_stop)

    def terminate_instances(self, cluster_name_on_cloud: str,
                            provider_config: Dict[str, Any]) -> None:
        spec = self.spec
        client = spec.adaptor.client()
        ctx = self._ctx(cluster_name_on_cloud, None, provider_config)

        def _terminate():
            if spec.prepare_context:
                spec.prepare_context(client, ctx)
            if spec.terminate_all is not None:
                spec.terminate_all(client, ctx)
                return
            for inst in spec.list_instances(client, ctx):
                state = spec.state(inst)
                if state == 'terminated' and not spec.terminate_terminated:
                    continue
                spec.terminate(client, ctx, inst)

        self._classified(_terminate)

    def query_instances(self, cluster_name_on_cloud: str,
                        provider_config: Dict[str, Any]
                        ) -> Dict[str, Optional[str]]:
        spec = self.spec
        client = spec.adaptor.client()
        # Region-scoped where the cloud's listing supports it: names
        # collide across regions after a failover, and a dying
        # other-region twin must not shadow the real node's status.
        ctx = self._ctx(cluster_name_on_cloud,
                        provider_config.get('region'), provider_config)

        def _query():
            if spec.prepare_context:
                spec.prepare_context(client, ctx)
            out: Dict[str, Optional[str]] = {}
            for inst in spec.list_instances(client, ctx):
                state = spec.state(inst)
                if state == 'terminated':
                    continue
                out[spec.name_of(inst)] = state
            return out

        return self._classified(_query)

    def get_cluster_info(self, region: str, cluster_name_on_cloud: str,
                         provider_config: Dict[str, Any]
                         ) -> common.ClusterInfo:
        spec = self.spec
        client = spec.adaptor.client()
        ctx = self._ctx(cluster_name_on_cloud, region, provider_config)

        def _info():
            if spec.prepare_context:
                spec.prepare_context(client, ctx)
            instances: Dict[str, common.InstanceInfo] = {}
            head_name = f'{cluster_name_on_cloud}-0'
            head_id: Optional[str] = None
            for inst in spec.list_instances(client, ctx):
                if spec.state(inst) != 'running':
                    continue
                name = spec.name_of(inst)
                instances[name] = common.InstanceInfo(
                    instance_id=name, hosts=[spec.host_info(inst)],
                    status='running', tags={})
                if name == head_name:
                    head_id = name
            if head_id is None and instances:
                head_id = sorted(instances)[0]
            return common.ClusterInfo(
                instances=instances, head_instance_id=head_id,
                provider_name=spec.provider,
                provider_config=provider_config,
                ssh_user=provider_config.get('ssh_user', spec.ssh_user),
                ssh_private_key=provider_config.get('ssh_private_key'))

        return self._classified(_info)

    def get_command_runners(self, cluster_info: common.ClusterInfo):
        return common.ssh_command_runners(cluster_info,
                                          self.spec.ssh_user)

    def export(self, module_globals: Dict[str, Any]) -> None:
        """Install the bound methods as the module-level provisioner
        interface (`run_instances`, `stop_instances`, ...)."""
        for fn in ('run_instances', 'wait_instances', 'stop_instances',
                   'terminate_instances', 'query_instances',
                   'get_cluster_info', 'get_command_runners'):
            module_globals[fn] = getattr(self, fn)
