"""Shared provision-layer types + the lifecycle plumbing every
flat-VM cloud repeats.

Reference analog: sky/provision/common.py (ProvisionConfig :39,
ProvisionRecord :63, InstanceInfo :92, ClusterInfo :109). TPU-first shape:
one logical *node* may be backed by several host VMs (a pod slice);
`InstanceInfo.hosts` carries every host of the slice so gang execution can
fan out to all of them (reference num_ips_per_node,
cloud_vm_ray_backend.py:2613).
"""
import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional


@dataclasses.dataclass
class HostInfo:
    """One reachable host VM (a slice worker or a standalone VM)."""
    host_id: str
    internal_ip: str
    external_ip: Optional[str] = None
    ssh_port: int = 22

    def get_ip(self, use_internal: bool = False) -> str:
        if use_internal or not self.external_ip:
            return self.internal_ip
        return self.external_ip


@dataclasses.dataclass
class InstanceInfo:
    """One logical node: a VM, or a whole TPU slice with N hosts."""
    instance_id: str
    hosts: List[HostInfo]
    status: str = 'running'
    tags: Dict[str, str] = dataclasses.field(default_factory=dict)

    @property
    def num_hosts(self) -> int:
        return len(self.hosts)


@dataclasses.dataclass
class ProvisionConfig:
    """Everything a cloud provisioner needs to create instances."""
    provider_config: Dict[str, Any]      # cloud-specific deploy variables
    authentication_config: Dict[str, Any]
    node_config: Dict[str, Any]
    count: int                           # logical nodes (slices)
    tags: Dict[str, str] = dataclasses.field(default_factory=dict)
    resume_stopped_nodes: bool = True
    ports_to_open_on_launch: List[str] = dataclasses.field(
        default_factory=list)


@dataclasses.dataclass
class ProvisionRecord:
    """Result of run_instances."""
    provider_name: str
    region: str
    zone: Optional[str]
    cluster_name_on_cloud: str
    head_instance_id: str
    created_instance_ids: List[str]
    resumed_instance_ids: List[str] = dataclasses.field(default_factory=list)

    def is_instance_just_booted(self, instance_id: str) -> bool:
        return (instance_id in self.created_instance_ids or
                instance_id in self.resumed_instance_ids)


@dataclasses.dataclass
class ClusterInfo:
    """Queryable state of a provisioned cluster."""
    instances: Dict[str, InstanceInfo]
    head_instance_id: Optional[str]
    provider_name: str
    provider_config: Dict[str, Any] = dataclasses.field(default_factory=dict)
    ssh_user: str = ''
    ssh_private_key: Optional[str] = None
    # docker_user etc. would slot in here.

    def get_head_instance(self) -> Optional[InstanceInfo]:
        if self.head_instance_id is None:
            return None
        return self.instances.get(self.head_instance_id)

    def ordered_instances(self) -> List[InstanceInfo]:
        """Head first, then workers sorted by instance id (stable ranks)."""
        out = []
        head = self.get_head_instance()
        if head is not None:
            out.append(head)
        for iid in sorted(self.instances):
            if iid != self.head_instance_id:
                out.append(self.instances[iid])
        return out

    def all_hosts(self) -> List[HostInfo]:
        return [h for inst in self.ordered_instances() for h in inst.hosts]

    @property
    def num_instances(self) -> int:
        return len(self.instances)


# --- lifecycle plumbing shared by the flat-VM clouds ------------------------

def ssh_command_runners(cluster_info: ClusterInfo,
                        default_user: str,
                        use_internal: bool = False) -> List[Any]:
    """One SSHCommandRunner per host, head-host first — the
    get_command_runners body every SSH-reachable cloud shares."""
    from skypilot_tpu.utils import command_runner
    runners: List[Any] = []
    for inst in cluster_info.ordered_instances():
        for host in inst.hosts:
            runners.append(command_runner.SSHCommandRunner(
                host.get_ip(use_internal=use_internal),
                user=cluster_info.ssh_user or default_user,
                private_key=cluster_info.ssh_private_key,
                port=host.ssh_port))
    return runners


def wait_until_running(list_instances: Callable[[], List[Any]],
                       count: int,
                       state_of: Callable[[Any], str],
                       describe: Callable[[Any], str],
                       timeout: float = 900.0,
                       poll_seconds: float = 5.0) -> None:
    """Poll until `count` LIVE instances are all 'running'.

    Terminated/stopping leftovers (lingering API entries after a
    down, dying failover remnants) are excluded from the convergence
    check so a relaunch can't dead-wait on them.
    """
    from skypilot_tpu import exceptions
    deadline = time.time() + timeout
    while True:
        instances = list_instances()
        live = [i for i in instances
                if state_of(i) not in ('terminated', 'stopping')]
        if len(live) >= count and all(state_of(i) == 'running'
                                      for i in live):
            return
        if time.time() > deadline:
            states = {describe(i): state_of(i) for i in instances}
            raise exceptions.ProvisionError(
                f'Timed out waiting for running: {states}')
        time.sleep(poll_seconds)


def require_public_key(authentication_config: Dict[str, Any]) -> str:
    """The cluster SSH public key, or a clear error NOW — registering
    an empty key account-wide launches instances nobody can reach,
    failing much later with a confusing auth error."""
    from skypilot_tpu import exceptions
    key = (authentication_config or {}).get('ssh_public_key_content')
    if not key:
        raise exceptions.ProvisionError(
            'No SSH public key configured for this launch '
            '(authentication_config.ssh_public_key_content is empty).')
    return key


def refuse_unresumable(state: Optional[str], name: str) -> None:
    """Shared launch-loop guard: an instance in a transitional state
    ('stopping') must block relaunch — creating a same-name twin
    would orphan a billing instance."""
    from skypilot_tpu import exceptions
    if state is not None:
        raise exceptions.ProvisionError(
            f'Instance {name} is {state}; cannot make progress '
            '(retry when it settles).')
