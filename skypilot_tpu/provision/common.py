"""Shared provision-layer types.

Reference analog: sky/provision/common.py (ProvisionConfig :39,
ProvisionRecord :63, InstanceInfo :92, ClusterInfo :109). TPU-first shape:
one logical *node* may be backed by several host VMs (a pod slice);
`InstanceInfo.hosts` carries every host of the slice so gang execution can
fan out to all of them (reference num_ips_per_node,
cloud_vm_ray_backend.py:2613).
"""
import dataclasses
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class HostInfo:
    """One reachable host VM (a slice worker or a standalone VM)."""
    host_id: str
    internal_ip: str
    external_ip: Optional[str] = None
    ssh_port: int = 22

    def get_ip(self, use_internal: bool = False) -> str:
        if use_internal or not self.external_ip:
            return self.internal_ip
        return self.external_ip


@dataclasses.dataclass
class InstanceInfo:
    """One logical node: a VM, or a whole TPU slice with N hosts."""
    instance_id: str
    hosts: List[HostInfo]
    status: str = 'running'
    tags: Dict[str, str] = dataclasses.field(default_factory=dict)

    @property
    def num_hosts(self) -> int:
        return len(self.hosts)


@dataclasses.dataclass
class ProvisionConfig:
    """Everything a cloud provisioner needs to create instances."""
    provider_config: Dict[str, Any]      # cloud-specific deploy variables
    authentication_config: Dict[str, Any]
    node_config: Dict[str, Any]
    count: int                           # logical nodes (slices)
    tags: Dict[str, str] = dataclasses.field(default_factory=dict)
    resume_stopped_nodes: bool = True
    ports_to_open_on_launch: List[str] = dataclasses.field(
        default_factory=list)


@dataclasses.dataclass
class ProvisionRecord:
    """Result of run_instances."""
    provider_name: str
    region: str
    zone: Optional[str]
    cluster_name_on_cloud: str
    head_instance_id: str
    created_instance_ids: List[str]
    resumed_instance_ids: List[str] = dataclasses.field(default_factory=list)

    def is_instance_just_booted(self, instance_id: str) -> bool:
        return (instance_id in self.created_instance_ids or
                instance_id in self.resumed_instance_ids)


@dataclasses.dataclass
class ClusterInfo:
    """Queryable state of a provisioned cluster."""
    instances: Dict[str, InstanceInfo]
    head_instance_id: Optional[str]
    provider_name: str
    provider_config: Dict[str, Any] = dataclasses.field(default_factory=dict)
    ssh_user: str = ''
    ssh_private_key: Optional[str] = None
    # docker_user etc. would slot in here.

    def get_head_instance(self) -> Optional[InstanceInfo]:
        if self.head_instance_id is None:
            return None
        return self.instances.get(self.head_instance_id)

    def ordered_instances(self) -> List[InstanceInfo]:
        """Head first, then workers sorted by instance id (stable ranks)."""
        out = []
        head = self.get_head_instance()
        if head is not None:
            out.append(head)
        for iid in sorted(self.instances):
            if iid != self.head_instance_id:
                out.append(self.instances[iid])
        return out

    def all_hosts(self) -> List[HostInfo]:
        return [h for inst in self.ordered_instances() for h in inst.hosts]

    @property
    def num_instances(self) -> int:
        return len(self.instances)
