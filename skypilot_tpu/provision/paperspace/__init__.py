"""Paperspace provisioner — GPU machines on the shared REST driver.

Reference analog: sky/provision/paperspace/instance.py. Machines have
server-assigned ids; our deterministic `<cluster>-<i>` identity rides
the machine NAME. Stop/start are first-class; startup script installs
the cluster SSH key.
"""
import re
from typing import Any, Dict, List

from skypilot_tpu.adaptors import paperspace as ps_adaptor
from skypilot_tpu.provision import common, rest_driver

_STATE_MAP = {
    'provisioning': 'pending',
    'starting': 'pending',
    'restarting': 'pending',
    'ready': 'running',
    'stopping': 'stopping',
    'off': 'stopped',
    'upgrading': 'pending',
    'deleting': 'stopping',
    'deleted': 'terminated',
}


def _state(machine: Dict[str, Any]) -> str:
    return _STATE_MAP.get(str(machine.get('state', '')).lower(),
                          'pending')


def _list(client, ctx: rest_driver.Ctx) -> List[Dict[str, Any]]:
    pattern = re.compile(re.escape(ctx.cluster) + r'-\d+$')
    out: List[Dict[str, Any]] = []
    page = None
    while True:
        params = {'limit': '100'}
        if page:
            params['after'] = page
        resp = client.request('GET', '/machines', params=params)
        items = resp.get('items', [])
        out.extend(m for m in items
                   if pattern.fullmatch(m.get('name') or ''))
        # The API's own cursor, never a guessed one: a wrong cursor
        # would silently truncate the cluster out of terminate/stop.
        next_page = resp.get('nextPage') or ''
        if not resp.get('hasMore') or not items or not next_page \
                or next_page == page:
            return out
        page = next_page


def _create(client, ctx: rest_driver.Ctx, name: str) -> None:
    nc = ctx.nc
    public_key = common.require_public_key(
        ctx.config.authentication_config)
    client.request('POST', '/machines', json_body={
        'name': name,
        'machineType': nc.get('instance_type', ''),
        'templateId': nc.get('image_id') or 'tkni3aa4',
        'region': ctx.region,
        'diskSize': int(nc.get('disk_size', 100)),
        'publicIpType': 'dynamic',
        # Startup scripts run as root: write to the paperspace user's
        # home EXPLICITLY (~ would be /root, stranding the key);
        # single quotes keep the key literal.
        'startupScript': (
            'mkdir -p /home/paperspace/.ssh && '
            f"echo '{public_key}' "
            '>> /home/paperspace/.ssh/authorized_keys && '
            'chown -R paperspace:paperspace /home/paperspace/.ssh'),
    })


_SPEC = rest_driver.RestVmSpec(
    provider='paperspace',
    adaptor=ps_adaptor,
    ssh_user='paperspace',
    list_instances=_list,
    state=_state,
    name_of=lambda m: m['name'],
    create=_create,
    host_info=lambda m: common.HostInfo(
        host_id=str(m['id']),
        internal_ip=m.get('privateIp', '') or m.get('publicIp', ''),
        external_ip=m.get('publicIp')),
    terminate=lambda client, ctx, m: client.request(
        'DELETE', f'/machines/{m["id"]}'),
    # 'deleted' machines 404 on DELETE but the old per-cloud code
    # deleted unconditionally; keep skipping only nothing.
    terminate_terminated=True,
    stop=lambda client, ctx, m: client.request(
        'PATCH', f'/machines/{m["id"]}/stop'),
    resume=lambda client, ctx, m: client.request(
        'PATCH', f'/machines/{m["id"]}/start'),
)

rest_driver.RestVmDriver(_SPEC).export(globals())
