"""Paperspace provisioner — GPU machines behind the uniform interface.

Reference analog: sky/provision/paperspace/instance.py. Machines have
server-assigned ids; our deterministic `<cluster>-<i>` identity rides
the machine NAME. Stop/start are first-class; startup script installs
the cluster SSH key.
"""
import logging
import re
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.adaptors import paperspace as ps_adaptor
from skypilot_tpu.provision import common

logger = logging.getLogger(__name__)

_STATE_MAP = {
    'provisioning': 'pending',
    'starting': 'pending',
    'restarting': 'pending',
    'ready': 'running',
    'stopping': 'stopping',
    'off': 'stopped',
    'upgrading': 'pending',
    'deleting': 'stopping',
    'deleted': 'terminated',
}


def _state(machine: Dict[str, Any]) -> str:
    return _STATE_MAP.get(str(machine.get('state', '')).lower(),
                          'pending')


def _cluster_machines(client, cluster_name_on_cloud: str
                      ) -> List[Dict[str, Any]]:
    pattern = re.compile(re.escape(cluster_name_on_cloud) + r'-\d+$')
    out: List[Dict[str, Any]] = []
    page = None
    while True:
        params = {'limit': '100'}
        if page:
            params['after'] = page
        resp = client.request('GET', '/machines', params=params)
        items = resp.get('items', [])
        out.extend(m for m in items
                   if pattern.fullmatch(m.get('name') or ''))
        # The API's own cursor, never a guessed one: a wrong cursor
        # would silently truncate the cluster out of terminate/stop.
        next_page = resp.get('nextPage') or ''
        if not resp.get('hasMore') or not items or not next_page \
                or next_page == page:
            return out
        page = next_page


def run_instances(region: str, cluster_name_on_cloud: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    client = ps_adaptor.client()
    nc = {**config.provider_config, **config.node_config}
    existing = {m['name']: m for m in _cluster_machines(
        client, cluster_name_on_cloud)}
    created: List[str] = []
    resumed: List[str] = []
    try:
        public_key = common.require_public_key(
            config.authentication_config)
        for i in range(config.count):
            name = f'{cluster_name_on_cloud}-{i}'
            machine = existing.get(name)
            state = _state(machine) if machine else None
            if state in ('running', 'pending'):
                continue
            if state == 'stopped':
                if not config.resume_stopped_nodes:
                    raise exceptions.ProvisionError(
                        f'Machine {name} is stopped; pass '
                        'resume_stopped_nodes to restart it.')
                client.request(
                    'PATCH', f'/machines/{machine["id"]}/start')
                resumed.append(name)
                continue
            common.refuse_unresumable(state, name)
            client.request('POST', '/machines', json_body={
                'name': name,
                'machineType': nc.get('instance_type', ''),
                'templateId': nc.get('image_id') or 'tkni3aa4',
                'region': region,
                'diskSize': int(nc.get('disk_size', 100)),
                'publicIpType': 'dynamic',
                # Startup scripts run as root: write to the paperspace
                # user's home EXPLICITLY (~ would be /root, stranding
                # the key); single quotes keep the key literal.
                'startupScript': (
                    'mkdir -p /home/paperspace/.ssh && '
                    f"echo '{public_key}' "
                    '>> /home/paperspace/.ssh/authorized_keys && '
                    'chown -R paperspace:paperspace '
                    '/home/paperspace/.ssh'),
            })
            created.append(name)
        common.wait_until_running(
            lambda: _cluster_machines(client, cluster_name_on_cloud),
            config.count, _state, lambda m: m['name'],
            timeout=float(config.provider_config.get(
                'provision_timeout', 900)))
    except ps_adaptor.RestApiError as e:
        raise ps_adaptor.classify_api_error(e) from e
    return common.ProvisionRecord(
        provider_name='paperspace', region=region, zone=None,
        cluster_name_on_cloud=cluster_name_on_cloud,
        head_instance_id=f'{cluster_name_on_cloud}-0',
        created_instance_ids=created, resumed_instance_ids=resumed)


def wait_instances(region: str, cluster_name_on_cloud: str,
                   state: Optional[str] = None) -> None:
    del region, cluster_name_on_cloud, state  # run_instances waits


def stop_instances(cluster_name_on_cloud: str,
                   provider_config: Dict[str, Any]) -> None:
    client = ps_adaptor.client()
    for machine in _cluster_machines(client, cluster_name_on_cloud):
        if _state(machine) == 'running':
            client.request('PATCH',
                           f'/machines/{machine["id"]}/stop')


def terminate_instances(cluster_name_on_cloud: str,
                        provider_config: Dict[str, Any]) -> None:
    client = ps_adaptor.client()
    for machine in _cluster_machines(client, cluster_name_on_cloud):
        client.request('DELETE', f'/machines/{machine["id"]}')


def query_instances(cluster_name_on_cloud: str,
                    provider_config: Dict[str, Any]
                    ) -> Dict[str, Optional[str]]:
    client = ps_adaptor.client()
    out: Dict[str, Optional[str]] = {}
    for machine in _cluster_machines(client, cluster_name_on_cloud):
        state = _state(machine)
        if state == 'terminated':
            continue
        out[machine['name']] = state
    return out


def get_cluster_info(region: str, cluster_name_on_cloud: str,
                     provider_config: Dict[str, Any]) -> common.ClusterInfo:
    del region
    client = ps_adaptor.client()
    instances: Dict[str, common.InstanceInfo] = {}
    head_name = f'{cluster_name_on_cloud}-0'
    head_id: Optional[str] = None
    for machine in _cluster_machines(client, cluster_name_on_cloud):
        if _state(machine) != 'running':
            continue
        name = machine['name']
        instances[name] = common.InstanceInfo(
            instance_id=name,
            hosts=[common.HostInfo(
                host_id=str(machine['id']),
                internal_ip=machine.get('privateIp', '') or
                machine.get('publicIp', ''),
                external_ip=machine.get('publicIp'))],
            status='running', tags={})
        if name == head_name:
            head_id = name
    if head_id is None and instances:
        head_id = sorted(instances)[0]
    return common.ClusterInfo(
        instances=instances, head_instance_id=head_id,
        provider_name='paperspace', provider_config=provider_config,
        ssh_user=provider_config.get('ssh_user', 'paperspace'),
        ssh_private_key=provider_config.get('ssh_private_key'))


def get_command_runners(cluster_info: common.ClusterInfo):
    return common.ssh_command_runners(cluster_info, 'paperspace')
