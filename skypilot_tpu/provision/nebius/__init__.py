"""Nebius AI Cloud provisioner — H100/H200 platforms behind the
uniform interface.

Reference analog: sky/provision/nebius/instance.py (692 LoC over the
SDK). Instances live under a parent project; names are deterministic
(`<cluster>-<i>`) and the instance spec carries the platform + preset
split of the catalog instance type (`<platform>_<preset>`, e.g.
`gpu-h100-sxm_8gpu-128vcpu-1600gb`). Stop/start are first-class, so
autostop can stop (unlike the terminate-only neoclouds).
"""
import logging
import re
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.adaptors import nebius as nebius_adaptor
from skypilot_tpu.provision import common

logger = logging.getLogger(__name__)

_BASE = '/compute/v1/instances'

_STATE_MAP = {
    'CREATING': 'pending',
    'STARTING': 'pending',
    'RUNNING': 'running',
    'STOPPING': 'stopping',
    'STOPPED': 'stopped',
    'DELETING': 'stopping',
    'ERROR': 'terminated',
}


def _project(pc: Dict[str, Any]) -> str:
    project = pc.get('project_id') or nebius_adaptor.default_project_id()
    if not project:
        raise exceptions.ProvisionError(
            'Nebius project id missing: set nebius.project_id in config '
            'or NEBIUS_PROJECT_ID.')
    pc['project_id'] = project
    return project


def _state(inst: Dict[str, Any]) -> str:
    return _STATE_MAP.get(
        inst.get('status', {}).get('state', ''), 'pending')


def _cluster_instances(client, project: str, cluster_name_on_cloud: str
                       ) -> List[Dict[str, Any]]:
    # Exact `<cluster>-<index>` match (a bare prefix would also catch
    # cluster 'train-2' when tearing down 'train'), following
    # nextPageToken so big projects can't truncate a cluster away.
    pattern = re.compile(re.escape(cluster_name_on_cloud) + r'-\d+$')
    out: List[Dict[str, Any]] = []
    page_token = ''
    while True:
        params = {'parentId': project, 'pageSize': '500'}
        if page_token:
            params['pageToken'] = page_token
        resp = client.request('GET', _BASE, params=params)
        out.extend(
            inst for inst in resp.get('items', [])
            if pattern.fullmatch(inst.get('metadata', {}).get('name', '')))
        page_token = resp.get('nextPageToken', '')
        if not page_token:
            return out


def split_instance_type(instance_type: str) -> Dict[str, str]:
    """'gpu-h100-sxm_8gpu-128vcpu-1600gb' -> platform + preset."""
    platform, _, preset = instance_type.partition('_')
    return {'platform': platform, 'preset': preset}


def run_instances(region: str, cluster_name_on_cloud: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    pc = config.provider_config
    project = _project(pc)
    client = nebius_adaptor.client()
    nc = {**pc, **config.node_config}
    spec_bits = split_instance_type(nc.get('instance_type', ''))
    existing = {i['metadata']['name']: i for i in _cluster_instances(
        client, project, cluster_name_on_cloud)}
    created: List[str] = []
    resumed: List[str] = []
    try:
        for i in range(config.count):
            name = f'{cluster_name_on_cloud}-{i}'
            inst = existing.get(name)
            state = _state(inst) if inst else None
            if state in ('running', 'pending'):
                continue
            if state == 'stopped':
                if not config.resume_stopped_nodes:
                    raise exceptions.ProvisionError(
                        f'Instance {name} is stopped; pass '
                        'resume_stopped_nodes to restart it.')
                client.request(
                    'POST', f'{_BASE}/{inst["metadata"]["id"]}:start')
                resumed.append(name)
                continue
            ssh_user = config.authentication_config.get(
                'ssh_user', 'skytpu')
            public_key = common.require_public_key(
                config.authentication_config)
            body = {
                'metadata': {'parentId': project, 'name': name},
                'spec': {
                    'resources': {
                        'platform': spec_bits['platform'],
                        'preset': spec_bits['preset'],
                    },
                    'bootDisk': {
                        'attachMode': 'READ_WRITE',
                        'sizeGibibytes': int(nc.get('disk_size', 256)),
                        'sourceImageFamily':
                            nc.get('image_id') or 'ubuntu22.04-driverless',
                    },
                    'networkInterfaces': [{
                        'name': 'eth0',
                        'subnetId': nc.get('subnet_id', ''),
                        'ipAddress': {},
                        'publicIpAddress': {},
                    }],
                    'cloudInitUserData': (
                        '#cloud-config\n'
                        f'users:\n'
                        f'  - name: {ssh_user}\n'
                        '    sudo: ALL=(ALL) NOPASSWD:ALL\n'
                        '    shell: /bin/bash\n'
                        '    ssh_authorized_keys:\n'
                        f'      - {public_key}\n'),
                },
            }
            client.request('POST', _BASE, json_body=body)
            created.append(name)
        _wait_running(client, project, cluster_name_on_cloud,
                      config.count,
                      timeout=float(pc.get('provision_timeout', 900)))
    except nebius_adaptor.RestApiError as e:
        raise nebius_adaptor.classify_api_error(e) from e
    return common.ProvisionRecord(
        provider_name='nebius', region=region, zone=None,
        cluster_name_on_cloud=cluster_name_on_cloud,
        head_instance_id=f'{cluster_name_on_cloud}-0',
        created_instance_ids=created, resumed_instance_ids=resumed)


def _wait_running(client, project: str, cluster_name_on_cloud: str,
                  count: int, timeout: float = 900.0) -> None:
    common.wait_until_running(
        lambda: _cluster_instances(client, project,
                                   cluster_name_on_cloud),
        count, _state, lambda i: i['metadata']['name'],
        timeout=timeout)


def wait_instances(region: str, cluster_name_on_cloud: str,
                   state: Optional[str] = None) -> None:
    del region, cluster_name_on_cloud, state  # run_instances waits


def stop_instances(cluster_name_on_cloud: str,
                   provider_config: Dict[str, Any]) -> None:
    project = _project(provider_config)
    client = nebius_adaptor.client()
    for inst in _cluster_instances(client, project,
                                   cluster_name_on_cloud):
        if _state(inst) == 'running':
            client.request('POST',
                           f'{_BASE}/{inst["metadata"]["id"]}:stop')


def terminate_instances(cluster_name_on_cloud: str,
                        provider_config: Dict[str, Any]) -> None:
    project = _project(provider_config)
    client = nebius_adaptor.client()
    for inst in _cluster_instances(client, project,
                                   cluster_name_on_cloud):
        client.request('DELETE', f'{_BASE}/{inst["metadata"]["id"]}')


def query_instances(cluster_name_on_cloud: str,
                    provider_config: Dict[str, Any]
                    ) -> Dict[str, Optional[str]]:
    project = _project(provider_config)
    client = nebius_adaptor.client()
    out: Dict[str, Optional[str]] = {}
    for inst in _cluster_instances(client, project,
                                   cluster_name_on_cloud):
        state = _state(inst)
        if state == 'terminated':
            continue
        out[inst['metadata']['name']] = state
    return out


def get_cluster_info(region: str, cluster_name_on_cloud: str,
                     provider_config: Dict[str, Any]) -> common.ClusterInfo:
    del region
    project = _project(provider_config)
    client = nebius_adaptor.client()
    instances: Dict[str, common.InstanceInfo] = {}
    head_name = f'{cluster_name_on_cloud}-0'
    head_id: Optional[str] = None
    for inst in _cluster_instances(client, project,
                                   cluster_name_on_cloud):
        if _state(inst) != 'running':
            continue
        name = inst['metadata']['name']
        nic = (inst.get('status', {}).get('networkInterfaces')
               or [{}])[0]
        instances[name] = common.InstanceInfo(
            instance_id=name,
            hosts=[common.HostInfo(
                host_id=inst['metadata']['id'],
                internal_ip=nic.get('ipAddress', {}).get('address', ''),
                external_ip=nic.get('publicIpAddress', {})
                .get('address'))],
            status='running', tags={})
        if name == head_name:
            head_id = name
    if head_id is None and instances:
        head_id = sorted(instances)[0]
    return common.ClusterInfo(
        instances=instances, head_instance_id=head_id,
        provider_name='nebius', provider_config=provider_config,
        ssh_user=provider_config.get('ssh_user', 'skytpu'),
        ssh_private_key=provider_config.get('ssh_private_key'))


def get_command_runners(cluster_info: common.ClusterInfo):
    return common.ssh_command_runners(cluster_info, 'skytpu')
