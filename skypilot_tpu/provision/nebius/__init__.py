"""Nebius AI Cloud provisioner — H100/H200 platforms on the shared
REST driver.

Reference analog: sky/provision/nebius/instance.py (692 LoC over the
SDK). Instances live under a parent project; names are deterministic
(`<cluster>-<i>`) and the instance spec carries the platform + preset
split of the catalog instance type (`<platform>_<preset>`, e.g.
`gpu-h100-sxm_8gpu-128vcpu-1600gb`). Stop/start are first-class, so
autostop can stop (unlike the terminate-only neoclouds).
"""
import re
from typing import Any, Dict, List

from skypilot_tpu import exceptions
from skypilot_tpu.adaptors import nebius as nebius_adaptor
from skypilot_tpu.provision import common, rest_driver

_BASE = '/compute/v1/instances'

_STATE_MAP = {
    'CREATING': 'pending',
    'STARTING': 'pending',
    'RUNNING': 'running',
    'STOPPING': 'stopping',
    'STOPPED': 'stopped',
    'DELETING': 'stopping',
    'ERROR': 'terminated',
}


def _resolve_project(client, ctx: rest_driver.Ctx) -> None:
    del client
    pc = ctx.provider_config
    project = pc.get('project_id') or nebius_adaptor.default_project_id()
    if not project:
        raise exceptions.ProvisionError(
            'Nebius project id missing: set nebius.project_id in config '
            'or NEBIUS_PROJECT_ID.')
    pc['project_id'] = project
    ctx.data['project'] = project


def _state(inst: Dict[str, Any]) -> str:
    return _STATE_MAP.get(
        inst.get('status', {}).get('state', ''), 'pending')


def _list(client, ctx: rest_driver.Ctx) -> List[Dict[str, Any]]:
    # Exact `<cluster>-<index>` match (a bare prefix would also catch
    # cluster 'train-2' when tearing down 'train'), following
    # nextPageToken so big projects can't truncate a cluster away.
    pattern = re.compile(re.escape(ctx.cluster) + r'-\d+$')
    out: List[Dict[str, Any]] = []
    page_token = ''
    while True:
        params = {'parentId': ctx.data['project'], 'pageSize': '500'}
        if page_token:
            params['pageToken'] = page_token
        resp = client.request('GET', _BASE, params=params)
        out.extend(
            inst for inst in resp.get('items', [])
            if pattern.fullmatch(inst.get('metadata', {}).get('name', '')))
        page_token = resp.get('nextPageToken', '')
        if not page_token:
            return out


def split_instance_type(instance_type: str) -> Dict[str, str]:
    """'gpu-h100-sxm_8gpu-128vcpu-1600gb' -> platform + preset."""
    platform, _, preset = instance_type.partition('_')
    return {'platform': platform, 'preset': preset}


def _create(client, ctx: rest_driver.Ctx, name: str) -> None:
    nc = ctx.nc
    spec_bits = split_instance_type(nc.get('instance_type', ''))
    ssh_user = ctx.config.authentication_config.get('ssh_user', 'skytpu')
    public_key = common.require_public_key(
        ctx.config.authentication_config)
    body = {
        'metadata': {'parentId': ctx.data['project'], 'name': name},
        'spec': {
            'resources': {
                'platform': spec_bits['platform'],
                'preset': spec_bits['preset'],
            },
            'bootDisk': {
                'attachMode': 'READ_WRITE',
                'sizeGibibytes': int(nc.get('disk_size', 256)),
                'sourceImageFamily':
                    nc.get('image_id') or 'ubuntu22.04-driverless',
            },
            'networkInterfaces': [{
                'name': 'eth0',
                'subnetId': nc.get('subnet_id', ''),
                'ipAddress': {},
                'publicIpAddress': {},
            }],
            'cloudInitUserData': (
                '#cloud-config\n'
                f'users:\n'
                f'  - name: {ssh_user}\n'
                '    sudo: ALL=(ALL) NOPASSWD:ALL\n'
                '    shell: /bin/bash\n'
                '    ssh_authorized_keys:\n'
                f'      - {public_key}\n'),
        },
    }
    client.request('POST', _BASE, json_body=body)


def _host_info(inst: Dict[str, Any]) -> common.HostInfo:
    nic = (inst.get('status', {}).get('networkInterfaces') or [{}])[0]
    return common.HostInfo(
        host_id=inst['metadata']['id'],
        internal_ip=nic.get('ipAddress', {}).get('address', ''),
        external_ip=nic.get('publicIpAddress', {}).get('address'))


_SPEC = rest_driver.RestVmSpec(
    provider='nebius',
    adaptor=nebius_adaptor,
    ssh_user='skytpu',
    list_instances=_list,
    state=_state,
    name_of=lambda inst: inst['metadata']['name'],
    create=_create,
    host_info=_host_info,
    terminate=lambda client, ctx, inst: client.request(
        'DELETE', f'{_BASE}/{inst["metadata"]["id"]}'),
    # ERROR-state instances map to 'terminated' but still hold quota:
    # delete them too.
    terminate_terminated=True,
    stop=lambda client, ctx, inst: client.request(
        'POST', f'{_BASE}/{inst["metadata"]["id"]}:stop'),
    resume=lambda client, ctx, inst: client.request(
        'POST', f'{_BASE}/{inst["metadata"]["id"]}:start'),
    prepare_context=_resolve_project,
)

rest_driver.RestVmDriver(_SPEC).export(globals())
