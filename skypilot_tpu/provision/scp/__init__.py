"""SCP (Samsung Cloud Platform) provisioner on the shared REST driver.

Reference analog: sky/provision/scp/instance.py (signed open-API
requests). Virtual servers carry our deterministic `<cluster>-<i>`
names; the service zone is the region; the cluster SSH key rides the
init script. Stop/start are first-class.
"""
import re
from typing import Any, Dict, List

from skypilot_tpu.adaptors import scp as scp_adaptor
from skypilot_tpu.provision import common, rest_driver

_BASE = '/virtual-server/v2/virtual-servers'

_STATE_MAP = {
    'CREATING': 'pending',
    'EDITING': 'pending',
    'STARTING': 'pending',
    'RESTARTING': 'pending',
    'RUNNING': 'running',
    'STOPPING': 'stopping',
    'TERMINATING': 'stopping',
    'STOPPED': 'stopped',
    'TERMINATED': 'terminated',
    'ERROR': 'terminated',
}


def _state(server: Dict[str, Any]) -> str:
    return _STATE_MAP.get(
        str(server.get('virtualServerState', '')).upper(), 'pending')


def _list(client, ctx: rest_driver.Ctx) -> List[Dict[str, Any]]:
    pattern = re.compile(re.escape(ctx.cluster) + r'-\d+$')
    resp = client.request('GET', _BASE, params={'size': '200'})
    items = resp.get('contents', resp.get('content', []))
    return [s for s in items
            if pattern.fullmatch(s.get('virtualServerName') or '')]


def _create(client, ctx: rest_driver.Ctx, name: str) -> None:
    nc = ctx.nc
    public_key = common.require_public_key(
        ctx.config.authentication_config)
    client.request('POST', _BASE, json_body={
        'virtualServerName': name,
        'serverType': nc.get('instance_type', ''),
        'serviceZoneId': ctx.region,
        'imageId': nc.get('image_id') or nc.get('default_image_id', ''),
        'blockStorage': {
            'blockStorageName': f'{name}-boot',
            'diskSize': int(nc.get('disk_size', 100)),
        },
        'nic': {'natEnabled': True},
        'initialScript': {
            'encodingType': 'plain',
            'initialScriptShell': 'bash',
            'initialScriptContent': (
                'mkdir -p /root/.ssh && '
                f"echo '{public_key}' >> /root/.ssh/authorized_keys"),
        },
    })


_SPEC = rest_driver.RestVmSpec(
    provider='scp',
    adaptor=scp_adaptor,
    ssh_user='root',
    list_instances=_list,
    state=_state,
    name_of=lambda s: s['virtualServerName'],
    create=_create,
    host_info=lambda s: common.HostInfo(
        host_id=str(s['virtualServerId']),
        internal_ip=s.get('ip', ''),
        external_ip=s.get('natIp')),
    terminate=lambda client, ctx, s: client.request(
        'DELETE', f'{_BASE}/{s["virtualServerId"]}'),
    stop=lambda client, ctx, s: client.request(
        'POST', f'{_BASE}/{s["virtualServerId"]}/stop'),
    resume=lambda client, ctx, s: client.request(
        'POST', f'{_BASE}/{s["virtualServerId"]}/start'),
)

rest_driver.RestVmDriver(_SPEC).export(globals())
