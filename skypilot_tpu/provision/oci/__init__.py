"""OCI provisioner — compartment-scoped compute on the shared REST
driver.

Reference analog: sky/provision/oci/instance.py + query_utils.py (oci
SDK). Instances live in a compartment; our deterministic
`<cluster>-<i>` identity rides displayName. Start/stop are instance
actions; addresses come from the instance's VNIC (ListVnicAttachments
→ GetVnic), which `_list` resolves for running instances so the
driver's host_info stays a pure extraction.
"""
import re
from typing import Any, Dict, List

from skypilot_tpu import exceptions
from skypilot_tpu.adaptors import oci as oci_adaptor
from skypilot_tpu.provision import common, rest_driver

_STATE_MAP = {
    'MOVING': 'pending',
    'PROVISIONING': 'pending',
    'CREATING_IMAGE': 'pending',
    'STARTING': 'pending',
    'RUNNING': 'running',
    'STOPPING': 'stopping',
    'TERMINATING': 'stopping',
    'STOPPED': 'stopped',
    'TERMINATED': 'terminated',
}


def _compartment(ctx: rest_driver.Ctx) -> str:
    pc = ctx.provider_config
    compartment = (pc.get('compartment_id')
                   or oci_adaptor.default_compartment_id())
    if not compartment:
        raise exceptions.ProvisionError(
            'OCI compartment id missing: set oci.compartment_id in '
            'config or OCI_COMPARTMENT_ID (or a tenancy in '
            '~/.oci/config).')
    pc['compartment_id'] = compartment
    ctx.data['compartment'] = compartment
    return compartment


def _resolve_compartment(client, ctx: rest_driver.Ctx) -> None:
    del client
    _compartment(ctx)


def _state(inst: Dict[str, Any]) -> str:
    return _STATE_MAP.get(inst.get('lifecycleState', ''), 'pending')


def _vnic_ips(client, compartment: str, inst: Dict[str, Any]) -> None:
    """Stash privateIp/publicIp on the instance dict from its VNIC."""
    attachments = client.request(
        'GET', '/vnicAttachments/',
        params={'compartmentId': compartment,
                'instanceId': inst['id']})
    items = (attachments if isinstance(attachments, list)
             else attachments.get('items', []))
    for att in items:
        if att.get('lifecycleState') not in (None, 'ATTACHED'):
            continue
        vnic = client.request('GET', f'/vnics/{att["vnicId"]}')
        inst['privateIp'] = vnic.get('privateIp', '')
        inst['publicIp'] = vnic.get('publicIp')
        return


def _list(client, ctx: rest_driver.Ctx) -> List[Dict[str, Any]]:
    compartment = ctx.data.get('compartment') or _compartment(ctx)
    pattern = re.compile(re.escape(ctx.cluster) + r'-\d+$')
    resp = client.request('GET', '/instances/',
                          params={'compartmentId': compartment})
    items = resp if isinstance(resp, list) else resp.get('items', [])
    out = [i for i in items
           if pattern.fullmatch(i.get('displayName') or '')]
    for inst in out:
        if _state(inst) == 'running' and 'privateIp' not in inst:
            _vnic_ips(client, compartment, inst)
    return out


def _create(client, ctx: rest_driver.Ctx, name: str) -> None:
    nc = ctx.nc
    ad = nc.get('availability_domain') or nc.get('zone')
    if not ad:
        raise exceptions.ProvisionError(
            'OCI launch needs an availability domain (zone).')
    body = {
        'availabilityDomain': ad,
        'compartmentId': ctx.data['compartment'],
        'displayName': name,
        'shape': nc.get('instance_type', ''),
        'metadata': {'ssh_authorized_keys': common.require_public_key(
            ctx.config.authentication_config)},
        'sourceDetails': {
            'sourceType': 'image',
            'imageId': nc.get('image_id') or nc.get('default_image_id',
                                                    ''),
            'bootVolumeSizeInGBs': int(nc.get('disk_size', 100)),
        },
        'createVnicDetails': {
            'assignPublicIp': True,
            'subnetId': nc.get('subnet_id', ''),
        },
    }
    client.request('POST', '/instances/', json_body=body)


_SPEC = rest_driver.RestVmSpec(
    provider='oci',
    adaptor=oci_adaptor,
    ssh_user='ubuntu',
    list_instances=_list,
    state=_state,
    name_of=lambda inst: inst['displayName'],
    create=_create,
    host_info=lambda inst: common.HostInfo(
        host_id=inst['id'],
        internal_ip=inst.get('privateIp', ''),
        external_ip=inst.get('publicIp')),
    terminate=lambda client, ctx, inst: client.request(
        'DELETE', f'/instances/{inst["id"]}'),
    stop=lambda client, ctx, inst: client.request(
        'POST', f'/instances/{inst["id"]}', params={'action': 'STOP'}),
    resume=lambda client, ctx, inst: client.request(
        'POST', f'/instances/{inst["id"]}', params={'action': 'START'}),
    prepare_context=_resolve_compartment,
)

rest_driver.RestVmDriver(_SPEC).export(globals())
