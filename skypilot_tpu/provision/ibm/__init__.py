"""IBM Cloud VPC provisioner on the shared REST driver.

Reference analog: sky/clouds/ibm.py + the legacy ibm node provider
(ibm_vpc SDK). Gen-2 VPC instances carry our deterministic
`<cluster>-<i>` names; the cluster SSH key is idempotently registered
as a VPC key, and a floating IP is attached at create time for public
reachability (VPC private IPs aren't routable from outside).
Stop/start are instance actions, so autostop can stop.
"""
import hashlib
import re
from typing import Any, Dict, List, Optional

from skypilot_tpu.adaptors import ibm as ibm_adaptor
from skypilot_tpu.provision import common, rest_driver

_STATE_MAP = {
    'pending': 'pending',
    'starting': 'pending',
    'restarting': 'pending',
    'resuming': 'pending',
    'running': 'running',
    'stopping': 'stopping',
    'pausing': 'stopping',
    'deleting': 'stopping',
    'stopped': 'stopped',
    'paused': 'stopped',
    'failed': 'terminated',
}


def _region(ctx: rest_driver.Ctx) -> Optional[str]:
    return ctx.region or ctx.provider_config.get('region')


def _state(inst: Dict[str, Any]) -> str:
    return _STATE_MAP.get(str(inst.get('status', '')).lower(),
                          'pending')


def _list(client, ctx: rest_driver.Ctx) -> List[Dict[str, Any]]:
    pattern = re.compile(re.escape(ctx.cluster) + r'-\d+$')
    region = _region(ctx)
    resp = client.request('GET', '/v1/instances',
                          params={'limit': '100'}, region=region)
    instances = [i for i in resp.get('instances', [])
                 if pattern.fullmatch(i.get('name') or '')]
    if any(_state(i) == 'running' and 'floating_ip' not in i
           for i in instances):
        fips = client.request('GET', '/v1/floating_ips',
                              params={'limit': '100'}, region=region)
        by_nic = {}
        for fip in fips.get('floating_ips', []):
            target = fip.get('target') or {}
            if target.get('id'):
                by_nic[target['id']] = fip.get('address')
        for inst in instances:
            nic = inst.get('primary_network_interface') or {}
            inst['floating_ip'] = by_nic.get(nic.get('id'))
    return instances


def _ensure_ssh_key(client, ctx: rest_driver.Ctx) -> None:
    """Idempotently register the cluster public key as a VPC key."""
    public_key = common.require_public_key(
        ctx.config.authentication_config)
    digest = hashlib.sha256(public_key.encode()).hexdigest()[:12]
    key_name = f'skytpu-{digest}'
    region = _region(ctx)
    existing = client.request('GET', '/v1/keys',
                              params={'limit': '100'}, region=region)
    for key in existing.get('keys', []):
        if key.get('name') == key_name:
            ctx.data['key_id'] = key['id']
            return
    created = client.request('POST', '/v1/keys', json_body={
        'name': key_name, 'public_key': public_key, 'type': 'rsa',
    }, region=region)
    ctx.data['key_id'] = created['id']


def _create(client, ctx: rest_driver.Ctx, name: str) -> None:
    nc = ctx.nc
    region = _region(ctx)
    body = {
        'name': name,
        'zone': {'name': nc.get('zone') or f'{region}-1'},
        'profile': {'name': nc.get('instance_type', '')},
        'vpc': {'id': nc.get('vpc_id', '')},
        'image': {'id': nc.get('image_id') or nc.get('default_image_id',
                                                     '')},
        'primary_network_interface': {
            'subnet': {'id': nc.get('subnet_id', '')},
        },
        'keys': [{'id': ctx.data['key_id']}],
        'boot_volume_attachment': {
            'volume': {
                'capacity': int(nc.get('disk_size', 100)),
                'profile': {'name': 'general-purpose'},
            },
        },
    }
    inst = client.request('POST', '/v1/instances', json_body=body,
                          region=region)
    nic = inst.get('primary_network_interface') or {}
    if nic.get('id'):
        # Public reachability: attach a floating IP to the primary NIC.
        client.request('POST', '/v1/floating_ips', json_body={
            'name': f'{name}-fip',
            'target': {'id': nic['id']},
        }, region=region)


def _host_info(inst: Dict[str, Any]) -> common.HostInfo:
    nic = inst.get('primary_network_interface') or {}
    internal = (nic.get('primary_ip') or {}).get('address') or \
        nic.get('primary_ipv4_address', '')
    return common.HostInfo(host_id=inst['id'], internal_ip=internal,
                           external_ip=inst.get('floating_ip'))


_SPEC = rest_driver.RestVmSpec(
    provider='ibm',
    adaptor=ibm_adaptor,
    ssh_user='ubuntu',
    list_instances=_list,
    state=_state,
    name_of=lambda inst: inst['name'],
    create=_create,
    host_info=_host_info,
    terminate=lambda client, ctx, inst: client.request(
        'DELETE', f'/v1/instances/{inst["id"]}',
        region=_region(ctx)),
    # 'failed' maps to terminated but still exists: delete it too.
    terminate_terminated=True,
    stop=lambda client, ctx, inst: client.request(
        'POST', f'/v1/instances/{inst["id"]}/actions',
        json_body={'type': 'stop'}, region=_region(ctx)),
    resume=lambda client, ctx, inst: client.request(
        'POST', f'/v1/instances/{inst["id"]}/actions',
        json_body={'type': 'start'}, region=_region(ctx)),
    prepare_launch=_ensure_ssh_key,
)

rest_driver.RestVmDriver(_SPEC).export(globals())
