"""Persistent-disk volumes for GCP clusters.

Reference analog: sky/provision/gcp/volume_utils.py:1 (create/attach
network volumes + device resolution). Volumes are declared in config
(`gcp.volumes: [{name, size_gb, type, mount_path}]`); run_instances
creates each disk idempotently, attaches it per node
(`<name>-<node-index>` for multi-node clusters), and the generated
mount script (format-if-blank + fstab) rides the VM startup script —
the standard GCP boot-time pattern, with a wait loop because the
attach lands after VM create.
"""
import logging
from typing import Any, Dict, List

from skypilot_tpu.adaptors import gcp as gcp_adaptor
from skypilot_tpu.provision import common

logger = logging.getLogger(__name__)


def _zone_url(project: str, zone: str) -> str:
    return (f'{gcp_adaptor.COMPUTE_API}/projects/{project}/zones/'
            f'{zone}')


def ensure_volume(project: str, zone: str, name: str, size_gb: int,
                  disk_type: str = 'pd-balanced') -> str:
    """Idempotently create a persistent disk; returns its URL."""
    t = gcp_adaptor.transport()
    url = f'{_zone_url(project, zone)}/disks'
    try:
        t.request('GET', f'{url}/{name}')
    except gcp_adaptor.GcpApiError as e:
        if e.status != 404:
            raise
        t.request('POST', url, json_body={
            'name': name,
            'sizeGb': str(size_gb),
            'type': f'zones/{zone}/diskTypes/{disk_type}',
        })
    return f'{url}/{name}'


def attach_volume(project: str, zone: str, vm_name: str,
                  disk_url: str, device_name: str) -> None:
    """Attach (idempotent: 400 'already attached' is success)."""
    t = gcp_adaptor.transport()
    try:
        t.request(
            'POST',
            f'{_zone_url(project, zone)}/instances/{vm_name}/attachDisk',
            json_body={'source': disk_url, 'deviceName': device_name,
                       'mode': 'READ_WRITE'})
    except gcp_adaptor.GcpApiError as e:
        if 'already' not in str(e).lower():
            raise


def delete_volume(project: str, zone: str, name: str) -> bool:
    """Delete; returns False when the disk didn't exist."""
    t = gcp_adaptor.transport()
    try:
        t.request('DELETE', f'{_zone_url(project, zone)}/disks/{name}')
        return True
    except gcp_adaptor.GcpApiError as e:
        if e.status != 404:
            raise
        return False


def _device_base(spec: Dict[str, Any],
                 cluster_name_on_cloud: str) -> str:
    """ONE name rule for attach + mount: a divergence here means the
    startup script waits on a device that never appears."""
    return spec.get('name') or f'{cluster_name_on_cloud}-vol'


def volume_names(spec: Dict[str, Any], cluster_name_on_cloud: str,
                 node_index: int) -> Dict[str, str]:
    """Disk + device names for one volume on one node. Per-node disks
    (a PD attaches read-write to one VM)."""
    base = _device_base(spec, cluster_name_on_cloud)
    return {'disk': f'{base}-{node_index}', 'device': base}


def mount_script(volumes: List[Dict[str, Any]],
                 cluster_name_on_cloud: str) -> str:
    """Startup-script fragment: wait for each device, format if blank,
    mount at the declared path. Runs as root at boot, AFTER the
    provisioner attaches the disk — hence the wait loop."""
    lines = []
    for spec in volumes:
        device = _device_base(spec, cluster_name_on_cloud)
        path = spec['mount_path']
        dev = f'/dev/disk/by-id/google-{device}'
        lines.append(
            f'for i in $(seq 1 60); do [ -e {dev} ] && break; sleep 2; '
            'done && '
            f'(blkid {dev} >/dev/null 2>&1 || '
            f'mkfs.ext4 -m 0 -F {dev}) && '
            f'mkdir -p {path} && '
            f'(mountpoint -q {path} || mount -o discard,defaults '
            f'{dev} {path})')
    return ' && '.join(lines)


def create_and_attach_all(config: common.ProvisionConfig,
                          cluster_name_on_cloud: str,
                          node_names: List[str]) -> None:
    """Provision every declared volume for every node."""
    pc = config.provider_config
    volumes = pc.get('volumes') or []
    if not volumes:
        return
    project, zone = pc['project_id'], pc['zone']
    for i, vm_name in enumerate(node_names):
        for spec in volumes:
            names = volume_names(spec, cluster_name_on_cloud, i)
            disk_url = ensure_volume(
                project, zone, names['disk'],
                int(spec.get('size_gb', 100)),
                spec.get('type', 'pd-balanced'))
            attach_volume(project, zone, vm_name, disk_url,
                          names['device'])


def delete_all(provider_config: Dict[str, Any],
               cluster_name_on_cloud: str,
               max_nodes: int = 1024) -> None:
    """Best-effort volume teardown at cluster terminate (only volumes
    not marked keep: true). Per-node disk names are dense (-0..-N-1),
    so the sweep walks upward and stops at the first index that never
    existed — no silent leak past an arbitrary cap."""
    volumes = provider_config.get('volumes') or []
    if not volumes:
        return
    project, zone = provider_config['project_id'], \
        provider_config['zone']
    for spec in volumes:
        if spec.get('keep'):
            continue
        for i in range(max_nodes):
            names = volume_names(spec, cluster_name_on_cloud, i)
            try:
                if not delete_volume(project, zone, names['disk']):
                    break  # dense names: first miss = past the end
            except gcp_adaptor.GcpApiError as e:
                # Best-effort: a disk still detaching (VM deletion op
                # in flight) must not fail the whole teardown.
                logger.warning('volume %s delete failed: %s',
                               names['disk'], e)
