"""Persistent-disk volumes for GCP clusters.

Reference analog: sky/provision/gcp/volume_utils.py:1 (create/attach
network volumes + device resolution). Volumes are declared in config
(`gcp.volumes: [{name, size_gb, type, mount_path}]`); run_instances
creates each disk idempotently, attaches it per node, and the
generated mount script (format-if-blank + fstab) rides the VM startup
script — the standard GCP boot-time pattern, with a wait loop because
the attach lands after VM create.

Disk naming: `{base}-{node_key}`, where node_key is the node index
for dense plain-compute names and the VM name's unique suffix for MIG
nodes (MIG names are `{cluster}-{random}`, so a positional index
would remap disks across nodes whenever membership churns). Teardown
enumerates by `{base}-` prefix instead of walking indices, so holes
from partial teardowns can't hide surviving disks.
"""
import logging
import re
from typing import Any, Dict, List

from skypilot_tpu import exceptions
from skypilot_tpu.adaptors import gcp as gcp_adaptor
from skypilot_tpu.provision import common

logger = logging.getLogger(__name__)


def _zone_url(project: str, zone: str) -> str:
    return (f'{gcp_adaptor.COMPUTE_API}/projects/{project}/zones/'
            f'{zone}')


CLUSTER_LABEL = 'skytpu-cluster'


def ensure_volume(project: str, zone: str, name: str, size_gb: int,
                  disk_type: str = 'pd-balanced',
                  cluster_name_on_cloud: str = '') -> str:
    """Idempotently create a persistent disk; returns its URL. The
    cluster label scopes ownership both ways: teardown must not sweep
    another cluster's same-named disks, and the exists-path must not
    silently ADOPT them — attaching another cluster's surviving
    `keep: true` disk would hand its data to the wrong cluster."""
    t = gcp_adaptor.transport()
    url = f'{_zone_url(project, zone)}/disks'
    try:
        existing = t.request('GET', f'{url}/{name}')
        owner = (existing.get('labels') or {}).get(CLUSTER_LABEL)
        if (owner and cluster_name_on_cloud
                and owner != cluster_name_on_cloud):
            raise exceptions.ProvisionError(
                f'Disk {name} already exists and belongs to cluster '
                f'{owner!r}; rename this volume or delete that disk.')
    except gcp_adaptor.GcpApiError as e:
        if e.status != 404:
            raise
        body = {
            'name': name,
            'sizeGb': str(size_gb),
            'type': f'zones/{zone}/diskTypes/{disk_type}',
        }
        if cluster_name_on_cloud:
            body['labels'] = {CLUSTER_LABEL: cluster_name_on_cloud}
        t.request('POST', url, json_body=body)
    return f'{url}/{name}'


def attach_volume(project: str, zone: str, vm_name: str,
                  disk_url: str, device_name: str) -> None:
    """Attach. Idempotent ONLY for 'already attached to this same VM';
    'already being used by <some other instance>' must surface — the
    node would otherwise boot diskless while its startup script waits
    on a device that never appears."""
    t = gcp_adaptor.transport()
    try:
        t.request(
            'POST',
            f'{_zone_url(project, zone)}/instances/{vm_name}/attachDisk',
            json_body={'source': disk_url, 'deviceName': device_name,
                       'mode': 'READ_WRITE'})
    except gcp_adaptor.GcpApiError as e:
        msg = str(e)
        # Exact path-segment match: 'c-1' must not match a message
        # naming '.../instances/c-10'.
        same_vm = re.search(
            rf'instances/{re.escape(vm_name)}(?![-\w])', msg)
        if 'already' in msg.lower() and (same_vm or
                                         f"'{vm_name}'" in msg):
            return
        raise


def delete_volume(project: str, zone: str, name: str) -> bool:
    """Delete; returns False when the disk didn't exist."""
    t = gcp_adaptor.transport()
    try:
        t.request('DELETE', f'{_zone_url(project, zone)}/disks/{name}')
        return True
    except gcp_adaptor.GcpApiError as e:
        if e.status != 404:
            raise
        return False


def list_cluster_disks(project: str, zone: str, prefix: str,
                       cluster_name_on_cloud: str) -> List[str]:
    """Names of this cluster's disks under `prefix`. Two guards: the
    remainder must be a single token (no '-'), so a sibling volume
    named `{base}-extra` isn't swept; and a disk labeled as belonging
    to a DIFFERENT cluster is skipped — two clusters declaring a
    volume with the same `name` coexist (suffix keying), and one's
    teardown must not delete the other's data. Unlabeled disks
    (created before labels existed) keep the prefix-only rule."""
    t = gcp_adaptor.transport()
    names: List[str] = []
    page_token = None
    while True:
        params = {'filter': f'name eq {prefix}.*'}
        if page_token:
            params['pageToken'] = page_token
        listing = t.request('GET', f'{_zone_url(project, zone)}/disks',
                            params=params)
        for item in listing.get('items', []):
            name = item.get('name', '')
            rest = name[len(prefix):]
            if not (name.startswith(prefix) and rest
                    and '-' not in rest):
                continue
            owner = (item.get('labels') or {}).get(CLUSTER_LABEL)
            if owner:
                if owner != cluster_name_on_cloud:
                    continue
            elif not rest.isdigit():
                # Unlabeled disks predate the ownership label; only
                # the legacy dense-numeric form is ours to sweep — a
                # hand-created 'data-backup' next to a volume named
                # 'data' must survive.
                continue
            names.append(name)
        page_token = listing.get('nextPageToken')
        if not page_token:
            return names


def _device_base(spec: Dict[str, Any], cluster_name_on_cloud: str,
                 vol_index: int) -> str:
    """ONE name rule for attach + mount: a divergence here means the
    startup script waits on a device that never appears. The first
    unnamed volume keeps the historical `{cluster}-vol` base (disks
    provisioned before the index suffix existed must keep resolving);
    later unnamed volumes get their list index so two anonymous
    volumes can't collide on disk/device name."""
    if spec.get('name'):
        return spec['name']
    suffix = '' if vol_index == 0 else str(vol_index)
    return f'{cluster_name_on_cloud}-vol{suffix}'


def _node_key(vm_name: str, node_index: int,
              cluster_name_on_cloud: str) -> str:
    """Disk-name key for one node. Dense plain-compute names
    (`{cluster}-{i}`) keep the index (historical naming); anything
    else — MIG names are `{cluster}-{random}` — keys by the VM name's
    unique suffix, which is stable across membership churn where a
    positional index is not."""
    if vm_name == f'{cluster_name_on_cloud}-{node_index}':
        return str(node_index)
    return vm_name.rsplit('-', 1)[-1]


def volume_names(spec: Dict[str, Any], cluster_name_on_cloud: str,
                 vol_index: int, node_key: str) -> Dict[str, str]:
    """Disk + device names for one volume on one node. Per-node disks
    (a PD attaches read-write to one VM)."""
    base = _device_base(spec, cluster_name_on_cloud, vol_index)
    return {'disk': f'{base}-{node_key}', 'device': base}


def mount_script(volumes: List[Dict[str, Any]],
                 cluster_name_on_cloud: str) -> str:
    """Startup-script fragment: wait for each device, format if blank,
    mount at the declared path. Runs as root at boot, AFTER the
    provisioner attaches the disk — hence the wait loop."""
    lines = []
    for vi, spec in enumerate(volumes):
        device = _device_base(spec, cluster_name_on_cloud, vi)
        path = spec.get('mount_path')
        if not path:
            # Attach-only volume: the device shows up under
            # /dev/disk/by-id/google-<name>; the user mounts it.
            continue
        dev = f'/dev/disk/by-id/google-{device}'
        lines.append(
            f'for i in $(seq 1 60); do [ -e {dev} ] && break; sleep 2; '
            'done && '
            f'(blkid {dev} >/dev/null 2>&1 || '
            f'mkfs.ext4 -m 0 -F {dev}) && '
            f'mkdir -p {path} && '
            f'(mountpoint -q {path} || mount -o discard,defaults '
            f'{dev} {path})')
    return ' && '.join(lines)


def create_and_attach_all(config: common.ProvisionConfig,
                          cluster_name_on_cloud: str,
                          node_names: List[str]) -> None:
    """Provision every declared volume for every node."""
    pc = config.provider_config
    volumes = pc.get('volumes') or []
    if not volumes:
        return
    project, zone = pc['project_id'], pc['zone']
    for i, vm_name in enumerate(node_names):
        key = _node_key(vm_name, i, cluster_name_on_cloud)
        for vi, spec in enumerate(volumes):
            names = volume_names(spec, cluster_name_on_cloud, vi, key)
            disk_url = ensure_volume(
                project, zone, names['disk'],
                int(spec.get('size_gb', 100)),
                spec.get('type', 'pd-balanced'),
                cluster_name_on_cloud=cluster_name_on_cloud)
            attach_volume(project, zone, vm_name, disk_url,
                          names['device'])


def delete_all(provider_config: Dict[str, Any],
               cluster_name_on_cloud: str) -> None:
    """Best-effort volume teardown at cluster terminate (only volumes
    not marked keep: true). Enumerates surviving disks by name prefix
    rather than walking indices, so holes from partial teardowns or
    MIG name churn can't shadow disks into a silent leak."""
    volumes = provider_config.get('volumes') or []
    if not volumes:
        return
    project, zone = provider_config['project_id'], \
        provider_config['zone']
    for vi, spec in enumerate(volumes):
        if spec.get('keep'):
            continue
        base = _device_base(spec, cluster_name_on_cloud, vi)
        try:
            names = list_cluster_disks(project, zone, f'{base}-',
                                       cluster_name_on_cloud)
        except gcp_adaptor.GcpApiError as e:
            logger.warning('volume listing for %s- failed: %s', base, e)
            continue
        for name in names:
            try:
                delete_volume(project, zone, name)
            except gcp_adaptor.GcpApiError as e:
                # Best-effort: a disk still detaching (VM deletion op
                # in flight) must not fail the whole teardown.
                logger.warning('volume %s delete failed: %s', name, e)
