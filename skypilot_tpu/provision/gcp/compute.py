"""Compute-Engine VM provisioning (controllers, CPU workers, GPU VMs).

Reference analog: sky/provision/gcp/instance_utils.py:311
(`GCPComputeInstance`). The TPU path lives in tpu.py; this covers the
plain-VM needs: jobs/serve controller hosts and CPU data-prep nodes.
"""
import logging
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.adaptors import gcp as gcp_adaptor
from skypilot_tpu.provision import common
from skypilot_tpu.provision.gcp import tpu as tpu_impl

logger = logging.getLogger(__name__)


def _project_zone(pc):
    project = pc.get('project_id')
    if not project:
        project = gcp_adaptor.default_project()
        pc['project_id'] = project
    return project, pc['zone']

CLUSTER_LABEL = tpu_impl.CLUSTER_LABEL
HEAD_LABEL = tpu_impl.HEAD_LABEL

_DEFAULT_IMAGE = ('projects/ubuntu-os-cloud/global/images/family/'
                  'ubuntu-2204-lts')

_STATE_MAP = {
    'PROVISIONING': 'pending',
    'STAGING': 'pending',
    'RUNNING': 'running',
    'STOPPING': 'stopping',
    'SUSPENDING': 'stopping',
    'SUSPENDED': 'stopped',
    'TERMINATED': 'stopped',  # compute TERMINATED == stopped-but-exists
    'REPAIRING': 'pending',
}


def _zone_url(project: str, zone: str) -> str:
    return f'{gcp_adaptor.COMPUTE_API}/projects/{project}/zones/{zone}'


def _list_cluster_vms(project: str, zone: str,
                      cluster_name_on_cloud: str) -> List[Dict[str, Any]]:
    t = gcp_adaptor.transport()
    out: List[Dict[str, Any]] = []
    page_token: Optional[str] = None
    while True:
        params = {
            'filter': f'labels.{CLUSTER_LABEL}={cluster_name_on_cloud}',
            'maxResults': '100',
        }
        if page_token:
            params['pageToken'] = page_token
        resp = t.request('GET', f'{_zone_url(project, zone)}/instances',
                         params=params)
        out.extend(resp.get('items', []))
        page_token = resp.get('nextPageToken')
        if not page_token:
            return out


def _vm_status(vm: Dict[str, Any]) -> str:
    return _STATE_MAP.get(vm.get('status', ''), 'pending')


def _create_body(config: common.ProvisionConfig, index: int,
                 cluster_name_on_cloud: str, project: str,
                 zone: str) -> Dict[str, Any]:
    pc = config.provider_config
    nc = {**pc, **config.node_config}
    name = f'{cluster_name_on_cloud}-{index}'
    labels = dict(nc.get('labels', {}))
    labels.update(config.tags)
    labels[CLUSTER_LABEL] = cluster_name_on_cloud
    labels[HEAD_LABEL] = 'true' if index == 0 else 'false'
    machine_type = nc.get('instance_type', 'n2-standard-8')
    network_interface: Dict[str, Any] = {
        'network': pc.get('network') or 'global/networks/default',
    }
    if pc.get('subnetwork'):
        network_interface['subnetwork'] = pc['subnetwork']
    if not pc.get('use_internal_ips', False):
        network_interface['accessConfigs'] = [{
            'name': 'External NAT', 'type': 'ONE_TO_ONE_NAT'}]
    body: Dict[str, Any] = {
        'name': name,
        'machineType': f'zones/{zone}/machineTypes/{machine_type}',
        'labels': labels,
        'disks': [{
            'boot': True,
            'autoDelete': True,
            'initializeParams': {
                'sourceImage': nc.get('image_id', _DEFAULT_IMAGE),
                'diskSizeGb': str(nc.get('disk_size', 256)),
            },
        }],
        'networkInterfaces': [network_interface],
        'metadata': {'items': []},
        'scheduling': {},
    }
    if nc.get('use_spot'):
        body['scheduling'] = {
            'provisioningModel': 'SPOT',
            'instanceTerminationAction': 'STOP',
        }
    ssh_pub = config.authentication_config.get('ssh_public_key_content')
    ssh_user = config.authentication_config.get('ssh_user', 'skytpu')
    if ssh_pub:
        body['metadata']['items'].append(
            {'key': 'ssh-keys', 'value': f'{ssh_user}:{ssh_pub}'})
    startup = nc.get('startup_script') or ''
    if nc.get('volumes'):
        from skypilot_tpu.provision.gcp import volumes as volumes_lib
        mount = volumes_lib.mount_script(nc['volumes'],
                                         cluster_name_on_cloud)
        startup = f'{startup}\n{mount}' if startup else mount
    if startup:
        body['metadata']['items'].append(
            {'key': 'startup-script', 'value': startup})
    return body


def _wait_zone_op(project: str, zone: str, op: Dict[str, Any],
                  timeout: float = 600.0) -> None:
    if not op.get('name'):
        return
    gcp_adaptor.wait_operation(
        op, f'{_zone_url(project, zone)}/operations/{op["name"]}',
        timeout=timeout)


def run_instances(region: str, cluster_name_on_cloud: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    pc = config.provider_config
    project, zone = _project_zone(pc)
    t = gcp_adaptor.transport()

    if pc.get('use_mig'):
        # MIG/DWS path: template properties are the VM body minus the
        # per-instance name and with an unqualified machineType.
        from skypilot_tpu.provision.gcp import mig as mig_lib
        from skypilot_tpu.provision.gcp import volumes as volumes_lib
        props = _create_body(config, 0, cluster_name_on_cloud, project,
                             zone)
        props.pop('name')
        props['machineType'] = props['machineType'].rsplit('/', 1)[-1]
        props['labels'].pop(HEAD_LABEL, None)

        def _list():
            return _list_cluster_vms(project, zone,
                                     cluster_name_on_cloud)

        record, node_names = mig_lib.run_instances(
            region, cluster_name_on_cloud, config, _list, props)
        if pc.get('volumes'):
            # Attach to every live node, not just the newly created
            # delta: create_and_attach_all is idempotent (disks key by
            # VM-name suffix), and a relaunch must heal a node whose
            # attach was interrupted last time.
            volumes_lib.create_and_attach_all(
                config, cluster_name_on_cloud, node_names)
        return record

    existing = {vm['name']: vm
                for vm in _list_cluster_vms(project, zone,
                                            cluster_name_on_cloud)}
    created: List[str] = []
    resumed: List[str] = []
    ops: List[Dict[str, Any]] = []
    for i in range(config.count):
        name = f'{cluster_name_on_cloud}-{i}'
        vm = existing.get(name)
        status = _vm_status(vm) if vm else None
        if status == 'running':
            continue
        try:
            if status == 'stopped' and config.resume_stopped_nodes:
                ops.append(t.request(
                    'POST',
                    f'{_zone_url(project, zone)}/instances/{name}/start'))
                resumed.append(name)
            elif status is None:
                ops.append(t.request(
                    'POST', f'{_zone_url(project, zone)}/instances',
                    json_body=_create_body(config, i, cluster_name_on_cloud,
                                           project, zone)))
                created.append(name)
            else:
                created.append(name)  # pending from a prior attempt
        except gcp_adaptor.GcpApiError as e:
            raise gcp_adaptor.classify_api_error(e) from e
    for op in ops:
        _wait_zone_op(project, zone, op,
                      timeout=float(pc.get('provision_timeout', 600)))
    if pc.get('volumes'):
        from skypilot_tpu.provision.gcp import volumes as volumes_lib
        node_names = [f'{cluster_name_on_cloud}-{i}'
                      for i in range(config.count)]
        volumes_lib.create_and_attach_all(config, cluster_name_on_cloud,
                                          node_names)
    return common.ProvisionRecord(
        provider_name='gcp', region=pc.get('region', zone.rsplit('-', 1)[0]),
        zone=zone, cluster_name_on_cloud=cluster_name_on_cloud,
        head_instance_id=f'{cluster_name_on_cloud}-0',
        created_instance_ids=created, resumed_instance_ids=resumed)


def stop_instances(cluster_name_on_cloud: str,
                   provider_config: Dict[str, Any]) -> None:
    project, zone = _project_zone(provider_config)
    t = gcp_adaptor.transport()
    for vm in _list_cluster_vms(project, zone, cluster_name_on_cloud):
        if _vm_status(vm) == 'running':
            op = t.request(
                'POST',
                f'{_zone_url(project, zone)}/instances/{vm["name"]}/stop')
            _wait_zone_op(project, zone, op)


def terminate_instances(cluster_name_on_cloud: str,
                        provider_config: Dict[str, Any]) -> None:
    project, zone = _project_zone(provider_config)
    t = gcp_adaptor.transport()
    if provider_config.get('use_mig'):
        # Deleting member VMs directly would just make the MIG heal
        # them: tear down resize requests + group + template instead.
        from skypilot_tpu.provision.gcp import mig as mig_lib
        from skypilot_tpu.provision.gcp import volumes as volumes_lib
        region = provider_config.get('region',
                                     zone.rsplit('-', 1)[0])
        mig_lib.cancel_and_delete(project, region, zone,
                                  cluster_name_on_cloud)
        volumes_lib.delete_all(provider_config, cluster_name_on_cloud)
        return
    ops = []
    for vm in _list_cluster_vms(project, zone, cluster_name_on_cloud):
        try:
            ops.append(t.request(
                'DELETE',
                f'{_zone_url(project, zone)}/instances/{vm["name"]}'))
        except gcp_adaptor.GcpApiError as e:
            if e.status != 404:
                raise
    for op in ops:
        _wait_zone_op(project, zone, op)
    if provider_config.get('volumes'):
        # After the VMs are gone (a PD can't be deleted while attached).
        from skypilot_tpu.provision.gcp import volumes as volumes_lib
        volumes_lib.delete_all(provider_config, cluster_name_on_cloud)


def query_instances(cluster_name_on_cloud: str,
                    provider_config: Dict[str, Any]
                    ) -> Dict[str, Optional[str]]:
    project, zone = _project_zone(provider_config)
    return {vm['name']: _vm_status(vm)
            for vm in _list_cluster_vms(project, zone,
                                        cluster_name_on_cloud)}


def get_cluster_info(region: str, cluster_name_on_cloud: str,
                     provider_config: Dict[str, Any]) -> common.ClusterInfo:
    del region
    project, zone = _project_zone(provider_config)
    instances: Dict[str, common.InstanceInfo] = {}
    head_id: Optional[str] = None
    for vm in _list_cluster_vms(project, zone, cluster_name_on_cloud):
        if _vm_status(vm) != 'running':
            continue
        nic = (vm.get('networkInterfaces') or [{}])[0]
        external = None
        for ac in nic.get('accessConfigs', []):
            external = ac.get('natIP') or external
        instances[vm['name']] = common.InstanceInfo(
            instance_id=vm['name'],
            hosts=[common.HostInfo(host_id=vm['name'],
                                   internal_ip=nic.get('networkIP', ''),
                                   external_ip=external)],
            status='running', tags=dict(vm.get('labels', {})))
        if vm.get('labels', {}).get(HEAD_LABEL) == 'true':
            head_id = vm['name']
    if head_id is None and instances:
        head_id = sorted(instances)[0]
    return common.ClusterInfo(
        instances=instances, head_instance_id=head_id,
        provider_name='gcp', provider_config=provider_config,
        ssh_user=provider_config.get('ssh_user', 'skytpu'),
        ssh_private_key=provider_config.get('ssh_private_key'))


def open_ports(cluster_name_on_cloud: str, ports: List[str],
               provider_config: Dict[str, Any]) -> None:
    """One firewall rule per cluster allowing the requested TCP ports."""
    project = provider_config['project_id']
    network = provider_config.get('network') or 'global/networks/default'
    t = gcp_adaptor.transport()
    rule_name = f'{cluster_name_on_cloud}-open-ports'
    body = {
        'name': rule_name,
        'network': network,
        'direction': 'INGRESS',
        'allowed': [{'IPProtocol': 'tcp', 'ports': list(ports)}],
        'sourceRanges': ['0.0.0.0/0'],
        'targetTags': [],
    }
    url = f'{gcp_adaptor.COMPUTE_API}/projects/{project}/global/firewalls'
    try:
        t.request('POST', url, json_body=body)
    except gcp_adaptor.GcpApiError as e:
        if e.status == 409:  # already exists: update in place
            t.request('PATCH', f'{url}/{rule_name}',
                      json_body={'allowed': body['allowed']})
        else:
            raise exceptions.ProvisionError(
                f'Failed to open ports {ports}: {e}') from e
