"""Managed instance groups + DWS queued capacity for GCP.

Reference analog: sky/provision/gcp/mig_utils.py:1 (regional instance
template + instanceGroupManagers + beta resizeRequests with
requestedRunDuration) — DWS (Dynamic Workload Scheduler) is how real
GPU/TPU fleets get scheduled capacity on GCP: the resize request
queues until capacity exists, then the MIG materializes VMs that run
for the requested duration.

Opt in with `gcp.use_mig: true`; `gcp.run_duration` (seconds) turns
the resize into a DWS queued request. VMs inherit the cluster label
from the template, so query/info/stop flow through the plain compute
paths; terminate detects the MIG and tears down group + template
(deleting member VMs directly would just make the MIG heal them).
"""
import logging
import time
from typing import Any, Dict, List, Tuple

from skypilot_tpu import exceptions
from skypilot_tpu.adaptors import gcp as gcp_adaptor
from skypilot_tpu.provision import common

logger = logging.getLogger(__name__)

_TEMPLATE_PREFIX = 'skytpu-it-'
_MIG_PREFIX = 'skytpu-mig-'


def template_name(cluster_name_on_cloud: str) -> str:
    return f'{_TEMPLATE_PREFIX}{cluster_name_on_cloud}'


def mig_name(cluster_name_on_cloud: str) -> str:
    return f'{_MIG_PREFIX}{cluster_name_on_cloud}'


def _region_url(project: str, region: str) -> str:
    return (f'{gcp_adaptor.COMPUTE_API}/projects/{project}/regions/'
            f'{region}')


def _zone_url(project: str, zone: str) -> str:
    return (f'{gcp_adaptor.COMPUTE_API}/projects/{project}/zones/'
            f'{zone}')


def _get_or_none(t, url: str):
    try:
        return t.request('GET', url)
    except gcp_adaptor.GcpApiError as e:
        if e.status == 404:
            return None
        raise


def ensure_instance_template(project: str, region: str,
                             cluster_name_on_cloud: str,
                             properties: Dict[str, Any]) -> str:
    """Idempotently create the regional instance template; returns its
    URL. Template properties are the VM create body minus per-instance
    fields (name, zone-qualified machineType)."""
    t = gcp_adaptor.transport()
    name = template_name(cluster_name_on_cloud)
    url = f'{_region_url(project, region)}/instanceTemplates'
    if _get_or_none(t, f'{url}/{name}') is None:
        t.request('POST', url, json_body={
            'name': name,
            'properties': {
                # DWS capacity must not consume reservations.
                'reservationAffinity': {
                    'consumeReservationType': 'NO_RESERVATION'},
                **properties,
            },
        })
    return f'{url}/{name}'


def ensure_mig(project: str, zone: str, cluster_name_on_cloud: str,
               template_url: str) -> str:
    """Idempotently create the zonal MIG at size 0 (resize requests
    grow it); returns the group name."""
    t = gcp_adaptor.transport()
    name = mig_name(cluster_name_on_cloud)
    url = f'{_zone_url(project, zone)}/instanceGroupManagers'
    if _get_or_none(t, f'{url}/{name}') is None:
        t.request('POST', url, json_body={
            'name': name,
            'instanceTemplate': template_url,
            'baseInstanceName': cluster_name_on_cloud,
            'targetSize': 0,
            # A failed heal must not loop-recreate broken capacity.
            'instanceLifecyclePolicy': {
                'defaultActionOnFailure': 'DO_NOTHING'},
            'updatePolicy': {'type': 'OPPORTUNISTIC'},
        })
    return name


def request_resize(project: str, zone: str, group: str, resize_by: int,
                   run_duration: int = 0) -> None:
    """Grow the MIG. With run_duration this is a DWS queued request
    (capacity arrives when the scheduler grants it, runs for the
    duration, then reclaims)."""
    t = gcp_adaptor.transport()
    body: Dict[str, Any] = {
        'name': f'{group}-resize-{int(time.time())}',
        'resizeBy': resize_by,
    }
    if run_duration:
        body['requestedRunDuration'] = {'seconds': int(run_duration)}
    t.request(
        'POST',
        f'{_zone_url(project, zone)}/instanceGroupManagers/{group}/'
        'resizeRequests', json_body=body)


def wait_group_size(project: str, zone: str, cluster_name_on_cloud: str,
                    count: int, list_vms, timeout: float = 1800.0
                    ) -> List[Dict[str, Any]]:
    """Poll until `count` labeled VMs are RUNNING (DWS requests can
    queue; the timeout is the capacity wait budget)."""
    deadline = time.time() + timeout
    while True:
        vms = [vm for vm in list_vms()
               if vm.get('status') == 'RUNNING']
        if len(vms) >= count:
            return vms
        if time.time() > deadline:
            raise exceptions.CapacityError(
                f'MIG {mig_name(cluster_name_on_cloud)}: {len(vms)}/'
                f'{count} VMs after {timeout:.0f}s (DWS request still '
                'queued?)')
        time.sleep(min(10.0, max(0.1, deadline - time.time())))


def cancel_and_delete(project: str, region: str, zone: str,
                      cluster_name_on_cloud: str) -> None:
    """Tear down resize requests, the group (and its VMs), and the
    template. Missing pieces are fine (partial creates, reruns)."""
    t = gcp_adaptor.transport()
    group = mig_name(cluster_name_on_cloud)
    group_url = (f'{_zone_url(project, zone)}/instanceGroupManagers/'
                 f'{group}')
    listing = _get_or_none(t, f'{group_url}/resizeRequests')
    for req in (listing or {}).get('items', []):
        if req.get('state') in ('ACCEPTED', 'CREATING'):
            try:
                t.request('POST',
                          f'{group_url}/resizeRequests/'
                          f'{req["name"]}:cancel')
            except gcp_adaptor.GcpApiError as e:
                if e.status != 404:
                    raise
    for url in (group_url,
                f'{_region_url(project, region)}/instanceTemplates/'
                f'{template_name(cluster_name_on_cloud)}'):
        try:
            t.request('DELETE', url)
        except gcp_adaptor.GcpApiError as e:
            if e.status != 404:
                raise


def run_instances(region: str, cluster_name_on_cloud: str,
                  config: common.ProvisionConfig, list_vms,
                  template_properties: Dict[str, Any]
                  ) -> Tuple[common.ProvisionRecord, List[str]]:
    """MIG/DWS provisioning path (compute.run_instances dispatches
    here on gcp.use_mig). Returns the record plus ALL running node
    names — the caller's volume attach wants the full membership, and
    returning it avoids a second listing (and the churn window between
    two listings)."""
    pc = config.provider_config
    project, zone = pc['project_id'], pc['zone']
    existing = [vm for vm in list_vms() if vm.get('status') == 'RUNNING']
    existing_names = {vm['name'] for vm in existing}
    missing = config.count - len(existing)
    if missing > 0:
        template_url = ensure_instance_template(
            project, region, cluster_name_on_cloud, template_properties)
        group = ensure_mig(project, zone, cluster_name_on_cloud,
                           template_url)
        request_resize(project, zone, group, missing,
                       run_duration=int(pc.get('run_duration', 0)))
        vms = wait_group_size(
            project, zone, cluster_name_on_cloud, config.count, list_vms,
            timeout=float(pc.get('provision_timeout', 1800)))
    else:
        vms = existing
    names = sorted(vm['name'] for vm in vms)
    # Only the delta is "created": pre-existing RUNNING VMs on a
    # relaunch already bootstrapped, and callers acting on new nodes
    # (volume attach, first-boot setup) must not see them as fresh —
    # same contract as the plain-compute path.
    created = sorted(set(names) - existing_names)
    return common.ProvisionRecord(
        provider_name='gcp', region=region, zone=zone,
        cluster_name_on_cloud=cluster_name_on_cloud,
        head_instance_id=names[0],
        created_instance_ids=created, resumed_instance_ids=[]), names
