"""TPU-VM slice provisioning over the tpu.googleapis.com v2 REST API.

Reference analog: sky/provision/gcp/instance_utils.py:1205
(`GCPTPUVMInstance`: create :1438, per-host SSH via `networkEndpoints`).
Differences: slices are first-class logical nodes (no TPU-node legacy
path), and preempted slices map straight to 'terminated' so the managed
-job recovery path terminates+relaunches (TPU slices cannot restart in
place; reference clouds/gcp.py:1066).
"""
import logging
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.adaptors import gcp as gcp_adaptor
from skypilot_tpu.provision import common

logger = logging.getLogger(__name__)


def _project_zone(pc):
    project = pc.get('project_id')
    if not project:
        project = gcp_adaptor.default_project()
        pc['project_id'] = project
    return project, pc['zone']

CLUSTER_LABEL = 'skytpu-cluster'
HEAD_LABEL = 'skytpu-head'

# TPU node states → provision-layer status.
_STATE_MAP = {
    'CREATING': 'pending',
    'STARTING': 'pending',
    'RESTARTING': 'pending',
    'REPAIRING': 'pending',
    'READY': 'running',
    'STOPPING': 'stopping',
    'STOPPED': 'stopped',
    'SUSPENDING': 'stopping',
    'SUSPENDED': 'stopped',
    'DELETING': 'terminated',
    'PREEMPTED': 'terminated',
    'TERMINATED': 'terminated',
    'HIDING': 'terminated',
    'HIDDEN': 'terminated',
    'UNHIDING': 'pending',
}


def _parent(project: str, zone: str) -> str:
    return (f'{gcp_adaptor.TPU_API}/projects/{project}/locations/{zone}')


def _node_name(cluster_name_on_cloud: str, index: int) -> str:
    return f'{cluster_name_on_cloud}-{index}'


def _list_cluster_nodes(project: str, zone: str,
                        cluster_name_on_cloud: str) -> List[Dict[str, Any]]:
    t = gcp_adaptor.transport()
    nodes: List[Dict[str, Any]] = []
    page_token: Optional[str] = None
    while True:
        params = {'pageSize': '100'}
        if page_token:
            params['pageToken'] = page_token
        resp = t.request('GET', f'{_parent(project, zone)}/nodes',
                         params=params)
        for node in resp.get('nodes', []):
            if node.get('labels', {}).get(
                    CLUSTER_LABEL) == cluster_name_on_cloud:
                nodes.append(node)
        page_token = resp.get('nextPageToken')
        if not page_token:
            return nodes


def _short_name(node: Dict[str, Any]) -> str:
    return node['name'].rsplit('/', 1)[-1]


def _node_status(node: Dict[str, Any]) -> str:
    return _STATE_MAP.get(node.get('state', ''), 'pending')


def _create_body(config: common.ProvisionConfig, index: int,
                 cluster_name_on_cloud: str) -> Dict[str, Any]:
    pc = config.provider_config
    # Deploy variables may arrive via provider_config (backend path) or
    # node_config (direct provision-API use); node_config wins.
    nc = {**pc, **config.node_config}
    labels = dict(nc.get('labels', {}))
    labels.update(config.tags)
    labels[CLUSTER_LABEL] = cluster_name_on_cloud
    labels[HEAD_LABEL] = 'true' if index == 0 else 'false'
    body: Dict[str, Any] = {
        'acceleratorType': nc['accelerator_type'],
        'runtimeVersion': nc['runtime_version'],
        'labels': labels,
        'networkConfig': {
            'enableExternalIps': not pc.get('use_internal_ips', False),
        },
        'schedulingConfig': {
            'preemptible': bool(nc.get('use_spot', False)),
        },
        'metadata': {},
    }
    if nc.get('use_spot') and pc.get('spot_as_spot', True):
        # Modern flag (spot) over legacy preemptible where supported.
        body['schedulingConfig'] = {'spot': True}
    network = pc.get('network')
    if network:
        body['networkConfig']['network'] = network
    subnetwork = pc.get('subnetwork')
    if subnetwork:
        body['networkConfig']['subnetwork'] = subnetwork
    ssh_pub = config.authentication_config.get('ssh_public_key_content')
    ssh_user = config.authentication_config.get('ssh_user', 'skytpu')
    if ssh_pub:
        body['metadata']['ssh-keys'] = f'{ssh_user}:{ssh_pub}'
    startup = nc.get('startup_script')
    if startup:
        body['metadata']['startup-script'] = startup
    reservation = nc.get('reservation')
    if reservation:
        body['schedulingConfig']['reserved'] = True
        body['reservedResource'] = {'reservationName': reservation}
    return body


def run_instances(region: str, cluster_name_on_cloud: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    del region  # TPU API is zonal
    pc = config.provider_config
    project, zone = _project_zone(pc)
    t = gcp_adaptor.transport()

    existing = {_short_name(n): n
                for n in _list_cluster_nodes(project, zone,
                                             cluster_name_on_cloud)}
    created: List[str] = []
    resumed: List[str] = []
    operations: List[Dict[str, Any]] = []
    for i in range(config.count):
        name = _node_name(cluster_name_on_cloud, i)
        node = existing.get(name)
        status = _node_status(node) if node else None
        if status == 'running':
            continue
        if status == 'stopped' and config.resume_stopped_nodes:
            try:
                op = t.request(
                    'POST', f'{_parent(project, zone)}/nodes/{name}:start')
            except gcp_adaptor.GcpApiError as e:
                raise gcp_adaptor.classify_api_error(e) from e
            operations.append(op)
            resumed.append(name)
            continue
        if status in ('pending', 'stopping'):
            # In-flight from a previous attempt; wait below via state poll.
            created.append(name)
            continue
        try:
            op = t.request('POST', f'{_parent(project, zone)}/nodes',
                           params={'nodeId': name},
                           json_body=_create_body(config, i,
                                                  cluster_name_on_cloud))
        except gcp_adaptor.GcpApiError as e:
            raise gcp_adaptor.classify_api_error(e) from e
        operations.append(op)
        created.append(name)

    for op in operations:
        if op.get('name'):
            gcp_adaptor.wait_operation(
                op, f'{gcp_adaptor.TPU_API}/{op["name"]}',
                timeout=float(pc.get('provision_timeout', 900)))
    _wait_all_ready(project, zone, cluster_name_on_cloud, config.count,
                    timeout=float(pc.get('provision_timeout', 900)))
    return common.ProvisionRecord(
        provider_name='gcp', region=pc.get('region', zone.rsplit('-', 1)[0]),
        zone=zone, cluster_name_on_cloud=cluster_name_on_cloud,
        head_instance_id=_node_name(cluster_name_on_cloud, 0),
        created_instance_ids=created, resumed_instance_ids=resumed)


def _wait_all_ready(project: str, zone: str, cluster_name_on_cloud: str,
                    count: int, timeout: float) -> None:
    import time
    deadline = time.time() + timeout
    while True:
        nodes = _list_cluster_nodes(project, zone, cluster_name_on_cloud)
        statuses = {_short_name(n): _node_status(n) for n in nodes}
        running = [n for n, s in statuses.items() if s == 'running']
        if len(running) >= count:
            return
        bad = {n: s for n, s in statuses.items()
               if s in ('terminated', 'stopped')}
        if bad:
            raise exceptions.CapacityError(
                f'TPU slice(s) failed to reach READY: {bad}')
        if time.time() > deadline:
            raise exceptions.ProvisionError(
                f'TPU slices not READY after {timeout:.0f}s: {statuses}')
        time.sleep(5)


def stop_instances(cluster_name_on_cloud: str,
                   provider_config: Dict[str, Any]) -> None:
    """Single-host TPU-VMs can stop; pod slices cannot (reference
    clouds/gcp.py:216) — callers must terminate those instead."""
    project, zone = _project_zone(provider_config)
    t = gcp_adaptor.transport()
    for node in _list_cluster_nodes(project, zone, cluster_name_on_cloud):
        if len(node.get('networkEndpoints', [])) > 1:
            raise exceptions.NotSupportedError(
                f'TPU pod slice {_short_name(node)} cannot be stopped; '
                'terminate it instead.')
        if _node_status(node) == 'running':
            op = t.request('POST', f'{gcp_adaptor.TPU_API}/{node["name"]}'
                           ':stop')
            if op.get('name'):
                gcp_adaptor.wait_operation(
                    op, f'{gcp_adaptor.TPU_API}/{op["name"]}')


def terminate_instances(cluster_name_on_cloud: str,
                        provider_config: Dict[str, Any]) -> None:
    project, zone = _project_zone(provider_config)
    t = gcp_adaptor.transport()
    ops = []
    for node in _list_cluster_nodes(project, zone, cluster_name_on_cloud):
        if _node_status(node) == 'terminated' and \
                node.get('state') != 'PREEMPTED':
            continue
        # PREEMPTED slices still occupy quota until deleted (reference
        # clouds/gcp.py:1066 cleanup-after-preemption).
        try:
            ops.append(t.request(
                'DELETE', f'{gcp_adaptor.TPU_API}/{node["name"]}'))
        except gcp_adaptor.GcpApiError as e:
            if e.status != 404:
                raise
    for op in ops:
        if op.get('name'):
            gcp_adaptor.wait_operation(
                op, f'{gcp_adaptor.TPU_API}/{op["name"]}')


def query_instances(cluster_name_on_cloud: str,
                    provider_config: Dict[str, Any]
                    ) -> Dict[str, Optional[str]]:
    project, zone = _project_zone(provider_config)
    return {
        _short_name(n): _node_status(n)
        for n in _list_cluster_nodes(project, zone, cluster_name_on_cloud)
    }


def get_cluster_info(region: str, cluster_name_on_cloud: str,
                     provider_config: Dict[str, Any]) -> common.ClusterInfo:
    del region
    project, zone = _project_zone(provider_config)
    instances: Dict[str, common.InstanceInfo] = {}
    head_id: Optional[str] = None
    for node in _list_cluster_nodes(project, zone, cluster_name_on_cloud):
        if _node_status(node) != 'running':
            continue
        name = _short_name(node)
        hosts = []
        for idx, ep in enumerate(node.get('networkEndpoints', [])):
            external = (ep.get('accessConfig') or {}).get('externalIp')
            hosts.append(common.HostInfo(
                host_id=f'{name}-w{idx}',
                internal_ip=ep.get('ipAddress', ''),
                external_ip=external))
        instances[name] = common.InstanceInfo(
            instance_id=name, hosts=hosts, status='running',
            tags=dict(node.get('labels', {})))
        if node.get('labels', {}).get(HEAD_LABEL) == 'true':
            head_id = name
    if head_id is None and instances:
        head_id = sorted(instances)[0]
    return common.ClusterInfo(
        instances=instances, head_instance_id=head_id,
        provider_name='gcp', provider_config=provider_config,
        ssh_user=provider_config.get('ssh_user', 'skytpu'),
        ssh_private_key=provider_config.get('ssh_private_key'))
