"""GCP provisioner: TPU-VM slices (the flagship path) + compute VMs.

Reference analog: sky/provision/gcp/ (GCPTPUVMInstance
instance_utils.py:1205, REST against tpu.googleapis.com, per-host SSH via
networkEndpoints; GCPComputeInstance :311). TPU-first shape: one logical
node == one TPU slice with N host VMs (`InstanceInfo.hosts`), so a
v5p-128 "cluster" of count=2 is two slices gang-scheduled together.

Routing: `provider_config['tpu_vm']` selects the TPU or compute
implementation; both expose the uniform provision API.
"""
from typing import Any, Dict, List, Optional

from skypilot_tpu.provision import common
from skypilot_tpu.provision.gcp import compute as compute_impl
from skypilot_tpu.provision.gcp import tpu as tpu_impl
from skypilot_tpu.utils import command_runner


def _impl(provider_config: Dict[str, Any]):
    return tpu_impl if provider_config.get('tpu_vm') else compute_impl


def run_instances(region: str, cluster_name_on_cloud: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    return _impl(config.provider_config).run_instances(
        region, cluster_name_on_cloud, config)


def wait_instances(region: str, cluster_name_on_cloud: str,
                   state: Optional[str] = None) -> None:
    # run_instances already waits for its long-running operations; both
    # implementations re-verify in get_cluster_info.
    del region, cluster_name_on_cloud, state


def stop_instances(cluster_name_on_cloud: str,
                   provider_config: Dict[str, Any]) -> None:
    _impl(provider_config).stop_instances(cluster_name_on_cloud,
                                          provider_config)


def terminate_instances(cluster_name_on_cloud: str,
                        provider_config: Dict[str, Any]) -> None:
    _impl(provider_config).terminate_instances(cluster_name_on_cloud,
                                               provider_config)


def query_instances(cluster_name_on_cloud: str,
                    provider_config: Dict[str, Any]
                    ) -> Dict[str, Optional[str]]:
    return _impl(provider_config).query_instances(cluster_name_on_cloud,
                                                  provider_config)


def get_cluster_info(region: str, cluster_name_on_cloud: str,
                     provider_config: Dict[str, Any]) -> common.ClusterInfo:
    return _impl(provider_config).get_cluster_info(region,
                                                   cluster_name_on_cloud,
                                                   provider_config)


def open_ports(cluster_name_on_cloud: str, ports: List[str],
               provider_config: Dict[str, Any]) -> None:
    compute_impl.open_ports(cluster_name_on_cloud, ports, provider_config)


def get_command_runners(cluster_info: common.ClusterInfo
                        ) -> List[command_runner.CommandRunner]:
    """One SSH runner per host; a pod slice contributes one per host VM."""
    runners: List[command_runner.CommandRunner] = []
    use_internal = bool(
        cluster_info.provider_config.get('use_internal_ips', False))
    for inst in cluster_info.ordered_instances():
        for host in inst.hosts:
            runners.append(command_runner.SSHCommandRunner(
                host.get_ip(use_internal=use_internal),
                user=cluster_info.ssh_user or 'skytpu',
                private_key=cluster_info.ssh_private_key,
                port=host.ssh_port))
    return runners
