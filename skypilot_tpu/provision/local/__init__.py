"""Local 'cloud' provisioner: instances are per-cluster runtime directories
on this machine; commands run as subprocesses.

This is a real provision-layer implementation (not a mock): the backend,
skylet job queue, log tailing and autostop all run against it, which is how
the end-to-end path stays testable with zero credentials (the reference
leans on moto for this; tests/common_test_fixtures.py:414).
"""
import json
import os
import shutil
from typing import Any, Dict, List, Optional

from skypilot_tpu.provision import common
from skypilot_tpu.utils import command_runner
from skypilot_tpu.utils import paths


def _cluster_dir(cluster_name_on_cloud: str) -> str:
    return os.path.join(paths.local_clusters_dir(), cluster_name_on_cloud)


def _meta_path(cluster_name_on_cloud: str) -> str:
    return os.path.join(_cluster_dir(cluster_name_on_cloud), 'metadata.json')


def _load_meta(cluster_name_on_cloud: str) -> Optional[Dict[str, Any]]:
    try:
        with open(_meta_path(cluster_name_on_cloud), 'r',
                  encoding='utf-8') as f:
            return json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return None


def run_instances(region: str, cluster_name_on_cloud: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    del region
    d = _cluster_dir(cluster_name_on_cloud)
    os.makedirs(d, exist_ok=True)
    meta = _load_meta(cluster_name_on_cloud)
    created: List[str] = []
    resumed: List[str] = []
    if meta is None or meta.get('state') == 'terminated':
        meta = {
            'state': 'running',
            'count': config.count,
            'runtime_dir': d,
        }
        created = [f'{cluster_name_on_cloud}-{i}'
                   for i in range(config.count)]
    elif meta.get('state') == 'stopped':
        meta['state'] = 'running'
        resumed = [f'{cluster_name_on_cloud}-{i}'
                   for i in range(meta['count'])]
    with open(_meta_path(cluster_name_on_cloud), 'w', encoding='utf-8') as f:
        json.dump(meta, f)
    return common.ProvisionRecord(
        provider_name='local', region='local', zone=None,
        cluster_name_on_cloud=cluster_name_on_cloud,
        head_instance_id=f'{cluster_name_on_cloud}-0',
        created_instance_ids=created,
        resumed_instance_ids=resumed)


def wait_instances(region: str, cluster_name_on_cloud: str,
                   state: Optional[str] = None) -> None:
    del region, cluster_name_on_cloud, state  # directories are instant


def stop_instances(cluster_name_on_cloud: str,
                   provider_config: Dict[str, Any]) -> None:
    del provider_config
    meta = _load_meta(cluster_name_on_cloud)
    if meta is not None:
        meta['state'] = 'stopped'
        with open(_meta_path(cluster_name_on_cloud), 'w',
                  encoding='utf-8') as f:
            json.dump(meta, f)


def terminate_instances(cluster_name_on_cloud: str,
                        provider_config: Dict[str, Any]) -> None:
    del provider_config
    d = _cluster_dir(cluster_name_on_cloud)
    _kill_cluster_processes(d)
    shutil.rmtree(d, ignore_errors=True)


def _kill_cluster_processes(cluster_dir: str) -> None:
    """A real VM's processes die with the VM; the local cloud must
    match: SIGKILL everything whose cmdline references this cluster's
    directory (skylet, gang drivers, job processes) at terminate."""
    import glob
    import signal
    marker = os.path.abspath(cluster_dir).encode()
    me = os.getpid()
    for pid_dir in glob.glob('/proc/[0-9]*'):
        try:
            pid = int(os.path.basename(pid_dir))
            if pid == me:
                continue
            with open(os.path.join(pid_dir, 'cmdline'), 'rb') as f:
                cmd = f.read()
        except (OSError, ValueError):
            continue
        if marker not in cmd:
            continue
        try:
            os.killpg(pid, signal.SIGKILL)
        except OSError:
            try:
                os.kill(pid, signal.SIGKILL)
            except OSError:
                pass


def query_instances(cluster_name_on_cloud: str,
                    provider_config: Dict[str, Any]
                    ) -> Dict[str, Optional[str]]:
    del provider_config
    meta = _load_meta(cluster_name_on_cloud)
    if meta is None:
        return {}
    return {f'{cluster_name_on_cloud}-{i}': meta.get('state', 'running')
            for i in range(meta.get('count', 1))}


def get_cluster_info(region: str, cluster_name_on_cloud: str,
                     provider_config: Dict[str, Any]) -> common.ClusterInfo:
    del region
    meta = _load_meta(cluster_name_on_cloud) or {'count': 1}
    instances = {}
    for i in range(meta.get('count', 1)):
        iid = f'{cluster_name_on_cloud}-{i}'
        instances[iid] = common.InstanceInfo(
            instance_id=iid,
            hosts=[common.HostInfo(host_id=iid, internal_ip='127.0.0.1')])
    return common.ClusterInfo(
        instances=instances,
        head_instance_id=f'{cluster_name_on_cloud}-0',
        provider_name='local',
        provider_config=dict(provider_config,
                             runtime_dir=_cluster_dir(cluster_name_on_cloud)),
        ssh_user=os.environ.get('USER', 'root'))


def open_ports(cluster_name_on_cloud: str, ports: List[str],
               provider_config: Dict[str, Any]) -> None:
    """No firewall on localhost — ports are inherently open."""
    del cluster_name_on_cloud, ports, provider_config


def get_command_runners(cluster_info: common.ClusterInfo) -> List:
    return [command_runner.LocalProcessRunner(h.host_id)
            for inst in cluster_info.ordered_instances()
            for h in inst.hosts]
