"""Fluidstack provisioner — GPU neocloud behind the uniform interface.

Reference analog: sky/provision/fluidstack/instance.py. Plain
instance lifecycle (create/list/stop/start/delete) with the SSH key
registered account-wide at launch; instances carry our deterministic
`<cluster>-<i>` names.
"""
import hashlib
import logging
import re
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.adaptors import fluidstack as fs_adaptor
from skypilot_tpu.provision import common

logger = logging.getLogger(__name__)

_STATUS_MAP = {
    'pending': 'pending',
    'provisioning': 'pending',
    'running': 'running',
    'stopping': 'stopping',
    'stopped': 'stopped',
    'terminated': 'terminated',
    'unhealthy': 'running',
}


def _state(inst: Dict[str, Any]) -> str:
    return _STATUS_MAP.get(str(inst.get('status', '')).lower(),
                           'pending')


def _cluster_instances(client, cluster_name_on_cloud: str
                       ) -> List[Dict[str, Any]]:
    pattern = re.compile(re.escape(cluster_name_on_cloud) + r'-\d+$')
    resp = client.request('GET', '/instances')
    instances = resp if isinstance(resp, list) else resp.get(
        'instances', [])
    return [i for i in instances
            if pattern.fullmatch(i.get('name') or '')]


def _ensure_ssh_key(client, public_key: str) -> str:
    digest = hashlib.sha256(public_key.encode()).hexdigest()[:12]
    key_name = f'skytpu-{digest}'
    resp = client.request('GET', '/ssh_keys')
    keys = resp if isinstance(resp, list) else resp.get('ssh_keys', [])
    for key in keys:
        if key.get('name') == key_name:
            return key_name
    client.request('POST', '/ssh_keys',
                   json_body={'name': key_name,
                              'public_key': public_key})
    return key_name


def run_instances(region: str, cluster_name_on_cloud: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    client = fs_adaptor.client()
    nc = {**config.provider_config, **config.node_config}
    existing = {i['name']: i for i in _cluster_instances(
        client, cluster_name_on_cloud)}
    created: List[str] = []
    resumed: List[str] = []
    try:
        key_name = _ensure_ssh_key(
            client,
            common.require_public_key(config.authentication_config))
        for i in range(config.count):
            name = f'{cluster_name_on_cloud}-{i}'
            inst = existing.get(name)
            state = _state(inst) if inst else None
            if state in ('running', 'pending'):
                continue
            if state == 'stopped':
                if not config.resume_stopped_nodes:
                    raise exceptions.ProvisionError(
                        f'Instance {name} is stopped; pass '
                        'resume_stopped_nodes to restart it.')
                client.request('PUT',
                               f'/instances/{inst["id"]}/start')
                resumed.append(name)
                continue
            common.refuse_unresumable(state, name)
            client.request('POST', '/instances', json_body={
                'name': name,
                'gpu_type': nc.get('gpu_type', ''),
                'gpu_count': int(nc.get('gpu_count', 1)),
                'ssh_key': key_name,
                'operating_system_label':
                    nc.get('image_id') or 'ubuntu_22_04_lts_nvidia',
                'region': region,
            })
            created.append(name)
        _wait_running(client, cluster_name_on_cloud, config.count,
                      timeout=float(config.provider_config.get(
                          'provision_timeout', 900)))
    except fs_adaptor.RestApiError as e:
        raise fs_adaptor.classify_api_error(e) from e
    return common.ProvisionRecord(
        provider_name='fluidstack', region=region, zone=None,
        cluster_name_on_cloud=cluster_name_on_cloud,
        head_instance_id=f'{cluster_name_on_cloud}-0',
        created_instance_ids=created, resumed_instance_ids=resumed)


def _wait_running(client, cluster_name_on_cloud: str, count: int,
                  timeout: float = 900.0) -> None:
    common.wait_until_running(
        lambda: _cluster_instances(client, cluster_name_on_cloud),
        count, _state, lambda i: i['name'], timeout=timeout)


def wait_instances(region: str, cluster_name_on_cloud: str,
                   state: Optional[str] = None) -> None:
    del region, cluster_name_on_cloud, state  # run_instances waits


def stop_instances(cluster_name_on_cloud: str,
                   provider_config: Dict[str, Any]) -> None:
    client = fs_adaptor.client()
    for inst in _cluster_instances(client, cluster_name_on_cloud):
        if _state(inst) == 'running':
            client.request('PUT', f'/instances/{inst["id"]}/stop')


def terminate_instances(cluster_name_on_cloud: str,
                        provider_config: Dict[str, Any]) -> None:
    client = fs_adaptor.client()
    for inst in _cluster_instances(client, cluster_name_on_cloud):
        if _state(inst) != 'terminated':
            client.request('DELETE', f'/instances/{inst["id"]}')


def query_instances(cluster_name_on_cloud: str,
                    provider_config: Dict[str, Any]
                    ) -> Dict[str, Optional[str]]:
    client = fs_adaptor.client()
    out: Dict[str, Optional[str]] = {}
    for inst in _cluster_instances(client, cluster_name_on_cloud):
        state = _state(inst)
        if state == 'terminated':
            continue
        out[inst['name']] = state
    return out


def get_cluster_info(region: str, cluster_name_on_cloud: str,
                     provider_config: Dict[str, Any]) -> common.ClusterInfo:
    del region
    client = fs_adaptor.client()
    instances: Dict[str, common.InstanceInfo] = {}
    head_name = f'{cluster_name_on_cloud}-0'
    head_id: Optional[str] = None
    for inst in _cluster_instances(client, cluster_name_on_cloud):
        if _state(inst) != 'running':
            continue
        name = inst['name']
        instances[name] = common.InstanceInfo(
            instance_id=name,
            hosts=[common.HostInfo(
                host_id=str(inst['id']),
                internal_ip=inst.get('private_ip', '') or
                inst.get('ip_address', ''),
                external_ip=inst.get('ip_address'))],
            status='running', tags={})
        if name == head_name:
            head_id = name
    if head_id is None and instances:
        head_id = sorted(instances)[0]
    return common.ClusterInfo(
        instances=instances, head_instance_id=head_id,
        provider_name='fluidstack', provider_config=provider_config,
        ssh_user=provider_config.get('ssh_user', 'ubuntu'),
        ssh_private_key=provider_config.get('ssh_private_key'))


def get_command_runners(cluster_info: common.ClusterInfo):
    return common.ssh_command_runners(cluster_info, 'ubuntu')
