"""Fluidstack provisioner — GPU neocloud on the shared REST driver.

Reference analog: sky/provision/fluidstack/instance.py. Plain
instance lifecycle (create/list/stop/start/delete) with the SSH key
registered account-wide at launch; instances carry our deterministic
`<cluster>-<i>` names.
"""
import hashlib
import re
from typing import Any, Dict, List

from skypilot_tpu.adaptors import fluidstack as fs_adaptor
from skypilot_tpu.provision import common, rest_driver

_STATUS_MAP = {
    'pending': 'pending',
    'provisioning': 'pending',
    'running': 'running',
    'stopping': 'stopping',
    'stopped': 'stopped',
    'terminated': 'terminated',
    'unhealthy': 'running',
}


def _state(inst: Dict[str, Any]) -> str:
    return _STATUS_MAP.get(str(inst.get('status', '')).lower(),
                           'pending')


def _list(client, ctx: rest_driver.Ctx) -> List[Dict[str, Any]]:
    pattern = re.compile(re.escape(ctx.cluster) + r'-\d+$')
    resp = client.request('GET', '/instances')
    instances = resp if isinstance(resp, list) else resp.get(
        'instances', [])
    return [i for i in instances
            if pattern.fullmatch(i.get('name') or '')]


def _ensure_ssh_key(client, ctx: rest_driver.Ctx) -> None:
    public_key = common.require_public_key(
        ctx.config.authentication_config)
    digest = hashlib.sha256(public_key.encode()).hexdigest()[:12]
    key_name = f'skytpu-{digest}'
    resp = client.request('GET', '/ssh_keys')
    keys = resp if isinstance(resp, list) else resp.get('ssh_keys', [])
    if not any(key.get('name') == key_name for key in keys):
        client.request('POST', '/ssh_keys',
                       json_body={'name': key_name,
                                  'public_key': public_key})
    ctx.data['key_name'] = key_name


def _create(client, ctx: rest_driver.Ctx, name: str) -> None:
    nc = ctx.nc
    client.request('POST', '/instances', json_body={
        'name': name,
        'gpu_type': nc.get('gpu_type', ''),
        'gpu_count': int(nc.get('gpu_count', 1)),
        'ssh_key': ctx.data['key_name'],
        'operating_system_label':
            nc.get('image_id') or 'ubuntu_22_04_lts_nvidia',
        'region': ctx.region,
    })


_SPEC = rest_driver.RestVmSpec(
    provider='fluidstack',
    adaptor=fs_adaptor,
    ssh_user='ubuntu',
    list_instances=_list,
    state=_state,
    name_of=lambda inst: inst['name'],
    create=_create,
    host_info=lambda inst: common.HostInfo(
        host_id=str(inst['id']),
        internal_ip=inst.get('private_ip', '') or
        inst.get('ip_address', ''),
        external_ip=inst.get('ip_address')),
    terminate=lambda client, ctx, inst: client.request(
        'DELETE', f'/instances/{inst["id"]}'),
    stop=lambda client, ctx, inst: client.request(
        'PUT', f'/instances/{inst["id"]}/stop'),
    resume=lambda client, ctx, inst: client.request(
        'PUT', f'/instances/{inst["id"]}/start'),
    prepare_launch=_ensure_ssh_key,
)

rest_driver.RestVmDriver(_SPEC).export(globals())
