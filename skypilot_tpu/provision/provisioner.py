"""Provisioner orchestration: create instances, wait, set up the runtime.

Reference analog: sky/provision/provisioner.py:104 (bulk_provision),
:365 (wait_for_ssh), :416 (_post_provision_setup), :671
(post_provision_runtime_setup). TPU-first difference in runtime setup:
instead of ray head/worker bootstrap, we write the slice topology file the
gang runner reads, ship the package, and start skylet — XLA owns the
intra-slice fabric, so there is no equivalent of `ray start`.
"""
import concurrent.futures
import json
import os
import shlex
import sys
from typing import Any, Callable, Dict, List, Optional, Tuple

from skypilot_tpu import exceptions
from skypilot_tpu import provision
from skypilot_tpu import envs
from skypilot_tpu.provision import common
from skypilot_tpu.resilience import faults
# Aliased: setup_runtime_dependencies has a `retries` parameter.
from skypilot_tpu.resilience import retries as retries_lib
from skypilot_tpu.skylet import constants as skylet_constants
from skypilot_tpu.utils import command_runner as runner_lib

_PKG_REMOTE_DIR = '~/.skytpu_runtime/pkg'


def bulk_provision(provider_name: str, region: str, zone: Optional[str],
                   cluster_name_on_cloud: str,
                   config: common.ProvisionConfig
                   ) -> common.ProvisionRecord:
    faults.inject('provision.launch', env_exc=exceptions.ProvisionError)
    record = provision.run_instances(provider_name, region,
                                     cluster_name_on_cloud, config)
    provision.wait_instances(provider_name, region, cluster_name_on_cloud,
                             state='running')
    if config.ports_to_open_on_launch:
        provision.open_ports(provider_name, cluster_name_on_cloud,
                             config.ports_to_open_on_launch,
                             config.provider_config)
    return record


def _parallel_over_hosts(fn: Callable, runners: List,
                         what: str) -> None:
    """Run fn(runner) on every host concurrently (reference
    _parallel_ssh_with_cache, instance_setup.py:139): pod slices have
    up to dozens of host VMs and serial SSH setup dominates
    launch-to-ready time."""
    if not runners:
        return
    if len(runners) == 1:
        fn(runners[0])
        return
    with concurrent.futures.ThreadPoolExecutor(
            max_workers=min(32, len(runners))) as pool:
        futures = {pool.submit(fn, r): r for r in runners}
        errors = []
        for fut, runner in futures.items():
            try:
                fut.result()
            except Exception as e:  # noqa: BLE001 — gather all failures
                errors.append(f'{runner.node_id}: {e}')
        if errors:
            raise exceptions.ClusterSetUpError(
                f'{what} failed on {len(errors)} host(s): '
                + '; '.join(errors))


def wait_for_connection(runners: List[runner_lib.CommandRunner],
                        timeout: float = 600.0) -> None:
    """Block until every host answers a trivial command (reference
    wait_for_ssh :365); hosts are polled in parallel. Fixed-interval
    poll (no jitter: one host hammers nobody) under the shared retry
    policy's deadline budget. The deadline is WALL-CLOCK from entry,
    shared by all hosts: queued hosts (pool capped at 32) must not
    each restart the budget."""
    import time
    deadline_ts = time.monotonic() + timeout

    def _wait_one(runner):
        remaining = deadline_ts - time.monotonic()
        if remaining <= 0:
            raise exceptions.ClusterSetUpError(
                f'unreachable after {timeout:.0f}s')
        policy = retries_lib.RetryPolicy(
            max_attempts=None, base_delay=5.0, max_delay=5.0,
            deadline=remaining, exponential=False, jitter=False)

        def _check() -> None:
            if not runner.check_connection():
                raise exceptions.ClusterSetUpError(
                    f'unreachable after {timeout:.0f}s')
        retries_lib.call(_check, policy=policy,
                         retry_on=(exceptions.ClusterSetUpError,),
                         describe=f'connection wait ({runner.node_id})')

    _parallel_over_hosts(_wait_one, runners, 'connection wait')


def runtime_dir_for(cluster_info: common.ClusterInfo) -> str:
    """Local clusters get a private runtime dir; remote ones the default."""
    if cluster_info.provider_name == 'local':
        return os.path.join(
            cluster_info.provider_config['runtime_dir'], 'runtime')
    return os.path.expanduser(skylet_constants.DEFAULT_RUNTIME_DIR)


def build_topology(cluster_name: str, cluster_info: common.ClusterInfo,
                   ssh_user: str = '', ssh_key: Optional[str] = None,
                   epoch: Optional[str] = None) -> Dict[str, Any]:
    """The file the gang runner reads: logical nodes -> host lists.

    `epoch` uniquely identifies one cluster incarnation: a
    terminate+relaunch under the same name writes a new epoch, which
    tells stale skylet/gang survivors of the old incarnation to die.
    Re-setup of a LIVE incarnation must pass the existing epoch so its
    daemons survive (post_provision_runtime_setup is idempotent)."""
    import uuid
    nodes = []
    local = cluster_info.provider_name == 'local'
    for inst in cluster_info.ordered_instances():
        hosts = []
        for h in inst.hosts:
            host: Dict[str, Any] = {'ip': h.get_ip(use_internal=True)}
            if local:
                host['local'] = True
            else:
                host['ssh_user'] = ssh_user or cluster_info.ssh_user
                host['ssh_key'] = ssh_key or cluster_info.ssh_private_key
                host['ssh_port'] = h.ssh_port
            hosts.append(host)
        nodes.append({'instance_id': inst.instance_id, 'hosts': hosts})
    topology = {'cluster_name': cluster_name, 'nodes': nodes,
                'epoch': epoch or uuid.uuid4().hex}
    # Liveness heartbeats (skylet HeartbeatEvent -> POST /api/v1/
    # heartbeat). The API server advertises its URL to executor
    # workers via env (app._advertise_url); config wins for
    # deployments where clusters reach the server through ingress.
    from skypilot_tpu import config as config_lib
    hb_url = config_lib.get_nested(('heartbeat', 'url'),
                                   envs.SKYTPU_API_SERVER_URL.get())
    if hb_url:
        topology['heartbeat'] = {'url': hb_url}
    return topology


def post_provision_runtime_setup(provider_name: str, cluster_name: str,
                                 cluster_info: common.ClusterInfo,
                                 stream_logs: bool = False
                                 ) -> Tuple[str, str]:
    """Make the cluster runnable: connectivity, topology file, package,
    skylet. Returns (runtime dir, topology epoch). Idempotent. The
    epoch is recorded in the cluster record so heartbeats from a
    previous incarnation of a same-named cluster are rejected."""
    from skypilot_tpu.utils import rich_utils
    runners = provision.get_command_runners(provider_name, cluster_info)
    with rich_utils.safe_status(
            f'[{cluster_name}] waiting for {len(runners)} host(s)'
            ) as spinner:
        wait_for_connection(runners)
        rt = runtime_dir_for(cluster_info)
        head = runners[0]
        local = isinstance(head, runner_lib.LocalProcessRunner)

        topology = build_topology(cluster_name, cluster_info,
                                  epoch=_existing_epoch(head, local, rt))
        if local:
            os.makedirs(rt, exist_ok=True)
            with open(skylet_constants.topology_path(rt), 'w',
                      encoding='utf-8') as f:
                json.dump(topology, f, indent=1)
        else:
            spinner.update(f'[{cluster_name}] installing runtime '
                           'dependencies')
            setup_runtime_dependencies(runners)
            spinner.update(f'[{cluster_name}] shipping package')
            _ship_package(runners)
            payload = shlex.quote(json.dumps(topology))
            for runner in runners:
                runner.run(f'mkdir -p {rt} && '
                           f'echo {payload} > {rt}/cluster_topology.json')

        spinner.update(f'[{cluster_name}] starting skylet')
        rc, out, err = head.run(
            _skylet_cli_cmd(local, rt, 'start-skylet'),
            require_outputs=True)
    if rc != 0:
        raise exceptions.ClusterSetUpError(
            f'Failed to start skylet on head: {err or out}')
    if not local:
        # Optional external log shipping (config logs.store).
        from skypilot_tpu.logs import agent as logs_agent
        logs_agent.setup_agent_on_cluster(runners, rt, cluster_name)
    return rt, topology['epoch']


def _existing_epoch(head, local: bool, rt: str) -> Optional[str]:
    """Epoch of an already-provisioned incarnation, if one is live:
    re-running setup must NOT mint a new epoch (that would tell the
    live skylet/gang daemons their cluster was replaced)."""
    if local:
        return skylet_constants.topology_epoch(rt)
    try:
        rc, out, _ = head.run(
            f'cat {shlex.quote(rt)}/cluster_topology.json',
            require_outputs=True)
        if rc == 0 and out.strip():
            return json.loads(out).get('epoch')
    except Exception:  # noqa: BLE001 — fresh host: no topology yet
        pass
    return None


# Runtime the framework needs on every host. TPU-VM images ship
# python3+jax; plain VMs (controllers, CPU workers) may lack jax — the
# probe installs only what is missing, so reprovision is cheap
# (reference instance_setup.py:206 setup_runtime_on_cluster, with its
# retry loop around flaky first-boot package managers).
_RUNTIME_PROBE = 'python3 -c "import sys; assert sys.version_info >= (3, 9)"'
_RUNTIME_INSTALL = (
    'python3 -c "import jax" 2>/dev/null || '
    'pip3 install --quiet "jax[cpu]" pyyaml')
_SETUP_RETRIES = 3
_SETUP_RETRY_GAP_SECONDS = 10.0


def setup_runtime_dependencies(
        runners: List[runner_lib.CommandRunner],
        retries: int = _SETUP_RETRIES,
        retry_gap: float = _SETUP_RETRY_GAP_SECONDS) -> None:
    """Probe + install the host runtime with retries: first boots race
    cloud-init/apt locks, so one failed install must not fail the whole
    provision. Full-jitter backoff de-synchronizes a pod's worth of
    hosts all racing the same first-boot apt lock."""
    policy = retries_lib.RetryPolicy(
        max_attempts=retries, base_delay=retry_gap,
        max_delay=retry_gap * 4)

    def _setup_one(runner):
        def _probe_install() -> None:
            rc, out, err = runner.run(
                f'{_RUNTIME_PROBE} && ({_RUNTIME_INSTALL})',
                require_outputs=True)
            if rc != 0:
                raise exceptions.ClusterSetUpError(
                    f'runtime setup failed: {err or out}')
        retries_lib.call(_probe_install, policy=policy,
                         retry_on=(exceptions.ClusterSetUpError,),
                         describe=f'runtime setup ({runner.node_id})')

    _parallel_over_hosts(_setup_one, runners, 'runtime setup')


def _ship_package(runners: List[runner_lib.CommandRunner]) -> None:
    """Rsync the framework package to every host (reference wheel shipping,
    sky/backends/wheel_utils.py — we sync sources instead of a wheel)."""
    import skypilot_tpu
    pkg_dir = os.path.dirname(os.path.abspath(skypilot_tpu.__file__))

    def _ship_one(runner):
        runner.run(f'mkdir -p {_PKG_REMOTE_DIR}')
        runner.rsync(pkg_dir, f'{_PKG_REMOTE_DIR}/', up=True,
                     excludes=['__pycache__', '*.pyc'])

    _parallel_over_hosts(_ship_one, runners, 'package shipping')


def _skylet_cli_cmd(local: bool, rt: str, subcmd: str, *args: str) -> str:
    """Shell command that invokes the skylet CLI on a host."""
    quoted = ' '.join(shlex.quote(a) for a in args)
    if local:
        import skypilot_tpu
        pkg_parent = os.path.dirname(os.path.dirname(
            os.path.abspath(skypilot_tpu.__file__)))
        py = shlex.quote(sys.executable)
        return (f'PYTHONPATH={shlex.quote(pkg_parent)}:$PYTHONPATH '
                f'{py} -m skypilot_tpu.skylet.cli '
                f'--runtime-dir {shlex.quote(rt)} {subcmd} {quoted}')
    return (f'PYTHONPATH={_PKG_REMOTE_DIR}:$PYTHONPATH python3 -m '
            f'skypilot_tpu.skylet.cli --runtime-dir {shlex.quote(rt)} '
            f'{subcmd} {quoted}')


def skylet_cli_cmd_for(runner: runner_lib.CommandRunner, rt: str,
                       subcmd: str, *args: str) -> str:
    return _skylet_cli_cmd(isinstance(runner, runner_lib.LocalProcessRunner),
                           rt, subcmd, *args)


def teardown_cluster(provider_name: str, cluster_name_on_cloud: str,
                     provider_config: Dict[str, Any],
                     terminate: bool) -> None:
    if terminate:
        provision.terminate_instances(provider_name, cluster_name_on_cloud,
                                      provider_config)
    else:
        provision.stop_instances(provider_name, cluster_name_on_cloud,
                                 provider_config)
