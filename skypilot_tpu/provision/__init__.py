"""Provision layer: uniform per-cloud low-level API, routed by module.

Reference analog: sky/provision/__init__.py:40 (`_route_to_cloud_impl`).
Every cloud module under skypilot_tpu/provision/<cloud>/ implements the
functions below with identical signatures.
"""
import importlib
from typing import Any, Dict, List, Optional

from skypilot_tpu.provision import common  # noqa: F401 (re-export)

def _impl(provider_name: str):
    name = provider_name.lower()
    try:
        return importlib.import_module(f'skypilot_tpu.provision.{name}')
    except ModuleNotFoundError:
        # Cloud names that aren't importable module names ('lambda' is
        # a keyword): the cloud policy class owns the real module path.
        from skypilot_tpu import clouds as clouds_lib
        return importlib.import_module(
            clouds_lib.get_cloud(name).provision_module())


def run_instances(provider_name: str, region: str,
                  cluster_name_on_cloud: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    return _impl(provider_name).run_instances(region, cluster_name_on_cloud,
                                              config)


def wait_instances(provider_name: str, region: str,
                   cluster_name_on_cloud: str,
                   state: Optional[str] = None) -> None:
    return _impl(provider_name).wait_instances(region, cluster_name_on_cloud,
                                               state)


def stop_instances(provider_name: str, cluster_name_on_cloud: str,
                   provider_config: Dict[str, Any]) -> None:
    return _impl(provider_name).stop_instances(cluster_name_on_cloud,
                                               provider_config)


def terminate_instances(provider_name: str, cluster_name_on_cloud: str,
                        provider_config: Dict[str, Any]) -> None:
    return _impl(provider_name).terminate_instances(cluster_name_on_cloud,
                                                    provider_config)


def query_instances(provider_name: str, cluster_name_on_cloud: str,
                    provider_config: Dict[str, Any]
                    ) -> Dict[str, Optional[str]]:
    """instance_id -> status ('running'|'stopped'|'terminated'|None)."""
    return _impl(provider_name).query_instances(cluster_name_on_cloud,
                                                provider_config)


def get_cluster_info(provider_name: str, region: str,
                     cluster_name_on_cloud: str,
                     provider_config: Dict[str, Any]) -> common.ClusterInfo:
    return _impl(provider_name).get_cluster_info(region,
                                                 cluster_name_on_cloud,
                                                 provider_config)


def open_ports(provider_name: str, cluster_name_on_cloud: str,
               ports: List[str], provider_config: Dict[str, Any]) -> None:
    impl = _impl(provider_name)
    if not hasattr(impl, 'open_ports'):
        from skypilot_tpu import exceptions
        raise exceptions.NotSupportedError(
            f'{provider_name} cannot open ports (requested: {ports}).')
    impl.open_ports(cluster_name_on_cloud, ports, provider_config)


def get_command_runners(provider_name: str, cluster_info: common.ClusterInfo
                        ) -> List:
    """One CommandRunner per *host* (a pod slice contributes several),
    ordered head-host first."""
    return _impl(provider_name).get_command_runners(cluster_info)
