"""Cudo Compute provisioner — project-scoped VMs behind the uniform
interface.

Reference analog: sky/provision/cudo/. VMs live under a project (like
Nebius); ids are our deterministic `<cluster>-<i>` names directly
(Cudo vm ids are caller-chosen), which makes every lookup exact.
"""
import logging
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.adaptors import cudo as cudo_adaptor
from skypilot_tpu.provision import common

logger = logging.getLogger(__name__)

_STATE_MAP = {
    'PENDING': 'pending',
    'CLONING': 'pending',
    'STARTING': 'pending',
    'ACTIVE': 'running',
    'STOPPING': 'stopping',
    'STOPPED': 'stopped',
    'DELETING': 'stopping',
    'DELETED': 'terminated',
    'FAILED': 'terminated',
}


def _project(pc: Dict[str, Any]) -> str:
    project = pc.get('project_id') or cudo_adaptor.default_project_id()
    if not project:
        raise exceptions.ProvisionError(
            'Cudo project id missing: set cudo.project_id in config '
            'or CUDO_PROJECT_ID.')
    pc['project_id'] = project
    return project


def _state(vm: Dict[str, Any]) -> str:
    return _STATE_MAP.get(str(vm.get('state', '')).upper(), 'pending')


def _cluster_vms(client, project: str, cluster_name_on_cloud: str
                 ) -> List[Dict[str, Any]]:
    import re
    pattern = re.compile(re.escape(cluster_name_on_cloud) + r'-\d+$')
    resp = client.request('GET', f'/v1/projects/{project}/vms')
    return [vm for vm in resp.get('VMs', resp.get('vms', []))
            if pattern.fullmatch(vm.get('id') or '')]


def run_instances(region: str, cluster_name_on_cloud: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    pc = config.provider_config
    project = _project(pc)
    client = cudo_adaptor.client()
    nc = {**pc, **config.node_config}
    existing = {vm['id']: vm for vm in _cluster_vms(
        client, project, cluster_name_on_cloud)}
    created: List[str] = []
    resumed: List[str] = []
    try:
        public_key = common.require_public_key(
            config.authentication_config)
        for i in range(config.count):
            name = f'{cluster_name_on_cloud}-{i}'
            vm = existing.get(name)
            state = _state(vm) if vm else None
            if state in ('running', 'pending'):
                continue
            if state == 'stopped':
                if not config.resume_stopped_nodes:
                    raise exceptions.ProvisionError(
                        f'VM {name} is stopped; pass '
                        'resume_stopped_nodes to restart it.')
                client.request(
                    'POST', f'/v1/projects/{project}/vms/{name}/start')
                resumed.append(name)
                continue
            common.refuse_unresumable(state, name)
            client.request(
                'POST', f'/v1/projects/{project}/vm', json_body={
                    'vmId': name,
                    'machineType': nc.get('instance_type', ''),
                    'dataCenterId': region,
                    'bootDiskImageId':
                        nc.get('image_id') or 'ubuntu-2204-nvidia-535',
                    'bootDiskSizeGib': int(nc.get('disk_size', 100)),
                    'sshKeySource': 'SSH_KEY_SOURCE_NONE',
                    'customSshKeys': [public_key],
                })
            created.append(name)
        common.wait_until_running(
            lambda: _cluster_vms(client, project, cluster_name_on_cloud),
            config.count, _state, lambda v: v['id'],
            timeout=float(pc.get('provision_timeout', 900)))
    except cudo_adaptor.RestApiError as e:
        raise cudo_adaptor.classify_api_error(e) from e
    return common.ProvisionRecord(
        provider_name='cudo', region=region, zone=None,
        cluster_name_on_cloud=cluster_name_on_cloud,
        head_instance_id=f'{cluster_name_on_cloud}-0',
        created_instance_ids=created, resumed_instance_ids=resumed)


def wait_instances(region: str, cluster_name_on_cloud: str,
                   state: Optional[str] = None) -> None:
    del region, cluster_name_on_cloud, state  # run_instances waits


def stop_instances(cluster_name_on_cloud: str,
                   provider_config: Dict[str, Any]) -> None:
    project = _project(provider_config)
    client = cudo_adaptor.client()
    for vm in _cluster_vms(client, project, cluster_name_on_cloud):
        if _state(vm) == 'running':
            client.request(
                'POST',
                f'/v1/projects/{project}/vms/{vm["id"]}/stop')


def terminate_instances(cluster_name_on_cloud: str,
                        provider_config: Dict[str, Any]) -> None:
    project = _project(provider_config)
    client = cudo_adaptor.client()
    for vm in _cluster_vms(client, project, cluster_name_on_cloud):
        client.request(
            'POST',
            f'/v1/projects/{project}/vms/{vm["id"]}/terminate')


def query_instances(cluster_name_on_cloud: str,
                    provider_config: Dict[str, Any]
                    ) -> Dict[str, Optional[str]]:
    project = _project(provider_config)
    client = cudo_adaptor.client()
    out: Dict[str, Optional[str]] = {}
    for vm in _cluster_vms(client, project, cluster_name_on_cloud):
        state = _state(vm)
        if state == 'terminated':
            continue
        out[vm['id']] = state
    return out


def get_cluster_info(region: str, cluster_name_on_cloud: str,
                     provider_config: Dict[str, Any]) -> common.ClusterInfo:
    del region
    project = _project(provider_config)
    client = cudo_adaptor.client()
    instances: Dict[str, common.InstanceInfo] = {}
    head_name = f'{cluster_name_on_cloud}-0'
    head_id: Optional[str] = None
    for vm in _cluster_vms(client, project, cluster_name_on_cloud):
        if _state(vm) != 'running':
            continue
        name = vm['id']
        nic = (vm.get('nics') or [{}])[0]
        instances[name] = common.InstanceInfo(
            instance_id=name,
            hosts=[common.HostInfo(
                host_id=name,
                internal_ip=nic.get('internalIpAddress', ''),
                external_ip=nic.get('externalIpAddress') or
                vm.get('externalIpAddress'))],
            status='running', tags={})
        if name == head_name:
            head_id = name
    if head_id is None and instances:
        head_id = sorted(instances)[0]
    return common.ClusterInfo(
        instances=instances, head_instance_id=head_id,
        provider_name='cudo', provider_config=provider_config,
        ssh_user=provider_config.get('ssh_user', 'root'),
        ssh_private_key=provider_config.get('ssh_private_key'))


def get_command_runners(cluster_info: common.ClusterInfo):
    return common.ssh_command_runners(cluster_info, 'root')
