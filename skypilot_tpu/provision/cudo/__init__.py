"""Cudo Compute provisioner — project-scoped VMs on the shared REST
driver.

Reference analog: sky/provision/cudo/. VMs live under a project (like
Nebius); ids are our deterministic `<cluster>-<i>` names directly
(Cudo vm ids are caller-chosen), which makes every lookup exact.
"""
import re
from typing import Any, Dict, List

from skypilot_tpu import exceptions
from skypilot_tpu.adaptors import cudo as cudo_adaptor
from skypilot_tpu.provision import common, rest_driver

_STATE_MAP = {
    'PENDING': 'pending',
    'CLONING': 'pending',
    'STARTING': 'pending',
    'ACTIVE': 'running',
    'STOPPING': 'stopping',
    'STOPPED': 'stopped',
    'DELETING': 'stopping',
    'DELETED': 'terminated',
    'FAILED': 'terminated',
}


def _resolve_project(client, ctx: rest_driver.Ctx) -> None:
    del client
    pc = ctx.provider_config
    project = pc.get('project_id') or cudo_adaptor.default_project_id()
    if not project:
        raise exceptions.ProvisionError(
            'Cudo project id missing: set cudo.project_id in config '
            'or CUDO_PROJECT_ID.')
    pc['project_id'] = project
    ctx.data['project'] = project


def _state(vm: Dict[str, Any]) -> str:
    return _STATE_MAP.get(str(vm.get('state', '')).upper(), 'pending')


def _list(client, ctx: rest_driver.Ctx) -> List[Dict[str, Any]]:
    pattern = re.compile(re.escape(ctx.cluster) + r'-\d+$')
    resp = client.request('GET',
                          f'/v1/projects/{ctx.data["project"]}/vms')
    return [vm for vm in resp.get('VMs', resp.get('vms', []))
            if pattern.fullmatch(vm.get('id') or '')]


def _create(client, ctx: rest_driver.Ctx, name: str) -> None:
    nc = ctx.nc
    client.request(
        'POST', f'/v1/projects/{ctx.data["project"]}/vm', json_body={
            'vmId': name,
            'machineType': nc.get('instance_type', ''),
            'dataCenterId': ctx.region,
            'bootDiskImageId':
                nc.get('image_id') or 'ubuntu-2204-nvidia-535',
            'bootDiskSizeGib': int(nc.get('disk_size', 100)),
            'sshKeySource': 'SSH_KEY_SOURCE_NONE',
            'customSshKeys': [common.require_public_key(
                ctx.config.authentication_config)],
        })


def _host_info(vm: Dict[str, Any]) -> common.HostInfo:
    nic = (vm.get('nics') or [{}])[0]
    return common.HostInfo(
        host_id=vm['id'],
        internal_ip=nic.get('internalIpAddress', ''),
        external_ip=nic.get('externalIpAddress') or
        vm.get('externalIpAddress'))


_SPEC = rest_driver.RestVmSpec(
    provider='cudo',
    adaptor=cudo_adaptor,
    ssh_user='root',
    list_instances=_list,
    state=_state,
    name_of=lambda vm: vm['id'],
    create=_create,
    host_info=_host_info,
    terminate=lambda client, ctx, vm: client.request(
        'POST',
        f'/v1/projects/{ctx.data["project"]}/vms/{vm["id"]}/terminate'),
    # FAILED VMs map to 'terminated' but still hold quota: terminate
    # them too.
    terminate_terminated=True,
    stop=lambda client, ctx, vm: client.request(
        'POST',
        f'/v1/projects/{ctx.data["project"]}/vms/{vm["id"]}/stop'),
    resume=lambda client, ctx, vm: client.request(
        'POST',
        f'/v1/projects/{ctx.data["project"]}/vms/{vm["id"]}/start'),
    prepare_context=_resolve_project,
)

rest_driver.RestVmDriver(_SPEC).export(globals())
