"""RunPod provisioner — container-based GPU cloud behind the uniform
interface.

Reference analog: sky/provision/runpod/instance.py (944 LoC over the
GraphQL SDK). A RunPod "instance" is a pod: we launch one pod per node
with a deterministic name (`<cluster>-<i>`), expose SSH over the pod's
public port mapping for 22/tcp, and map desiredStatus RUNNING/EXITED
onto running/stopped. Stop keeps the volume (resume restarts the same
pod); terminate deletes it.
"""
import logging
import re
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.adaptors import runpod as runpod_adaptor
from skypilot_tpu.provision import common

logger = logging.getLogger(__name__)

_DEFAULT_IMAGE = 'runpod/base:0.6.2-cuda12.4.1'

_STATUS_MAP = {
    'CREATED': 'pending',
    'RUNNING': 'running',
    'RESTARTING': 'pending',
    'EXITED': 'stopped',
    'PAUSED': 'stopped',
    'DEAD': 'terminated',
    'TERMINATED': 'terminated',
}


def _status(pod: Dict[str, Any]) -> str:
    return _STATUS_MAP.get(pod.get('desiredStatus', ''), 'pending')


def _cluster_pods(client, cluster_name_on_cloud: str
                  ) -> List[Dict[str, Any]]:
    resp = client.request('GET', '/pods')
    pods = resp if isinstance(resp, list) else resp.get('pods', [])
    # Exact `<cluster>-<index>` match: a bare prefix would also catch
    # cluster 'train-2' when tearing down cluster 'train'.
    pattern = re.compile(re.escape(cluster_name_on_cloud) + r'-\d+$')
    return [p for p in pods if pattern.fullmatch(p.get('name') or '')]


def run_instances(region: str, cluster_name_on_cloud: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    client = runpod_adaptor.client()
    nc = {**config.provider_config, **config.node_config}
    existing = {p['name']: p
                for p in _cluster_pods(client, cluster_name_on_cloud)}
    created: List[str] = []
    resumed: List[str] = []
    try:
        for i in range(config.count):
            name = f'{cluster_name_on_cloud}-{i}'
            pod = existing.get(name)
            status = _status(pod) if pod else None
            if status in ('running', 'pending'):
                continue
            if status == 'stopped':
                if not config.resume_stopped_nodes:
                    raise exceptions.ProvisionError(
                        f'Pod {name} is stopped; pass '
                        'resume_stopped_nodes to restart it.')
                client.request('POST', f'/pods/{pod["id"]}/start')
                resumed.append(name)
                continue
            body = {
                'name': name,
                'imageName': nc.get('image_id') or _DEFAULT_IMAGE,
                'gpuTypeIds': [nc['gpu_type']] if nc.get('gpu_type')
                else [],
                'gpuCount': int(nc.get('gpu_count', 0)),
                'cloudType': ('COMMUNITY' if nc.get('use_spot')
                              else 'SECURE'),
                'containerDiskInGb': int(nc.get('disk_size', 64)),
                'ports': ['22/tcp'],
                'env': {'PUBLIC_KEY': common.require_public_key(
                    config.authentication_config)},
                'dataCenterIds': [region] if region else [],
                'interruptible': bool(nc.get('use_spot')),
            }
            client.request('POST', '/pods', json_body=body)
            created.append(name)
        _wait_running(client, cluster_name_on_cloud, config.count,
                      timeout=float(config.provider_config.get(
                          'provision_timeout', 900)))
    except runpod_adaptor.RestApiError as e:
        raise runpod_adaptor.classify_api_error(e) from e
    return common.ProvisionRecord(
        provider_name='runpod', region=region, zone=None,
        cluster_name_on_cloud=cluster_name_on_cloud,
        head_instance_id=f'{cluster_name_on_cloud}-0',
        created_instance_ids=created, resumed_instance_ids=resumed)


def _wait_running(client, cluster_name_on_cloud: str, count: int,
                  timeout: float = 900.0) -> None:
    common.wait_until_running(
        lambda: _cluster_pods(client, cluster_name_on_cloud),
        count, _status, lambda p: p['name'], timeout=timeout)


def wait_instances(region: str, cluster_name_on_cloud: str,
                   state: Optional[str] = None) -> None:
    del region, cluster_name_on_cloud, state  # run_instances waits


def stop_instances(cluster_name_on_cloud: str,
                   provider_config: Dict[str, Any]) -> None:
    client = runpod_adaptor.client()
    for pod in _cluster_pods(client, cluster_name_on_cloud):
        if _status(pod) == 'running':
            client.request('POST', f'/pods/{pod["id"]}/stop')


def terminate_instances(cluster_name_on_cloud: str,
                        provider_config: Dict[str, Any]) -> None:
    client = runpod_adaptor.client()
    for pod in _cluster_pods(client, cluster_name_on_cloud):
        if _status(pod) != 'terminated':
            client.request('DELETE', f'/pods/{pod["id"]}')


def query_instances(cluster_name_on_cloud: str,
                    provider_config: Dict[str, Any]
                    ) -> Dict[str, Optional[str]]:
    client = runpod_adaptor.client()
    out: Dict[str, Optional[str]] = {}
    for pod in _cluster_pods(client, cluster_name_on_cloud):
        status = _status(pod)
        if status == 'terminated':
            continue
        out[pod['name']] = status
    return out


def _ssh_endpoint(pod: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Public (ip, port) for the pod's 22/tcp mapping.

    The REST surface returns `portMappings` as an object keyed by
    private port ({"22": 10341}) with the address in `publicIp`; the
    GraphQL-era shape is a list of dicts under runtime.ports. Handle
    both, and skip mappings whose public port isn't assigned yet.
    """
    mappings = pod.get('portMappings')
    if isinstance(mappings, dict):
        public = mappings.get('22')
        if public:
            return {'ip': pod.get('publicIp'), 'port': int(public)}
    elif isinstance(mappings, list):
        for mapping in mappings:
            if str(mapping.get('privatePort')) == '22' and \
                    mapping.get('publicPort'):
                return {'ip': mapping.get('ip') or pod.get('publicIp'),
                        'port': int(mapping['publicPort'])}
    for mapping in pod.get('runtime', {}).get('ports') or []:
        if str(mapping.get('privatePort')) == '22' and \
                mapping.get('isIpPublic', True) and \
                mapping.get('publicPort'):
            return {'ip': mapping.get('ip'),
                    'port': int(mapping['publicPort'])}
    return None


def get_cluster_info(region: str, cluster_name_on_cloud: str,
                     provider_config: Dict[str, Any]) -> common.ClusterInfo:
    del region
    client = runpod_adaptor.client()
    instances: Dict[str, common.InstanceInfo] = {}
    head_name = f'{cluster_name_on_cloud}-0'
    head_id: Optional[str] = None
    for pod in _cluster_pods(client, cluster_name_on_cloud):
        if _status(pod) != 'running':
            continue
        name = pod['name']
        endpoint = _ssh_endpoint(pod) or {}
        internal = pod.get('internalIp') or endpoint.get('ip') or ''
        instances[name] = common.InstanceInfo(
            instance_id=name,
            hosts=[common.HostInfo(
                host_id=pod['id'], internal_ip=internal,
                external_ip=endpoint.get('ip'),
                ssh_port=endpoint.get('port', 22))],
            status='running', tags={})
        if name == head_name:
            head_id = name
    if head_id is None and instances:
        head_id = sorted(instances)[0]
    return common.ClusterInfo(
        instances=instances, head_instance_id=head_id,
        provider_name='runpod', provider_config=provider_config,
        ssh_user='root',
        ssh_private_key=provider_config.get('ssh_private_key'))


def get_command_runners(cluster_info: common.ClusterInfo):
    return common.ssh_command_runners(cluster_info, 'root')
