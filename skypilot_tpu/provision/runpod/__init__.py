"""RunPod provisioner — container-based GPU cloud on the shared REST
driver.

Reference analog: sky/provision/runpod/instance.py (944 LoC over the
GraphQL SDK). A RunPod "instance" is a pod: we launch one pod per node
with a deterministic name (`<cluster>-<i>`), expose SSH over the pod's
public port mapping for 22/tcp, and map desiredStatus RUNNING/EXITED
onto running/stopped. Stop keeps the volume (resume restarts the same
pod); terminate deletes it.
"""
import re
from typing import Any, Dict, List, Optional

from skypilot_tpu.adaptors import runpod as runpod_adaptor
from skypilot_tpu.provision import common, rest_driver

_DEFAULT_IMAGE = 'runpod/base:0.6.2-cuda12.4.1'

_STATUS_MAP = {
    'CREATED': 'pending',
    'RUNNING': 'running',
    'RESTARTING': 'pending',
    'EXITED': 'stopped',
    'PAUSED': 'stopped',
    'DEAD': 'terminated',
    'TERMINATED': 'terminated',
}


def _status(pod: Dict[str, Any]) -> str:
    return _STATUS_MAP.get(pod.get('desiredStatus', ''), 'pending')


def _cluster_pods(client, cluster_name_on_cloud: str
                  ) -> List[Dict[str, Any]]:
    resp = client.request('GET', '/pods')
    pods = resp if isinstance(resp, list) else resp.get('pods', [])
    # Exact `<cluster>-<index>` match: a bare prefix would also catch
    # cluster 'train-2' when tearing down cluster 'train'.
    pattern = re.compile(re.escape(cluster_name_on_cloud) + r'-\d+$')
    return [p for p in pods if pattern.fullmatch(p.get('name') or '')]


def _create(client, ctx: rest_driver.Ctx, name: str) -> None:
    nc = ctx.nc
    body = {
        'name': name,
        'imageName': nc.get('image_id') or _DEFAULT_IMAGE,
        'gpuTypeIds': [nc['gpu_type']] if nc.get('gpu_type') else [],
        'gpuCount': int(nc.get('gpu_count', 0)),
        'cloudType': 'COMMUNITY' if nc.get('use_spot') else 'SECURE',
        'containerDiskInGb': int(nc.get('disk_size', 64)),
        'ports': ['22/tcp'],
        'env': {'PUBLIC_KEY': common.require_public_key(
            ctx.config.authentication_config)},
        'dataCenterIds': [ctx.region] if ctx.region else [],
        'interruptible': bool(nc.get('use_spot')),
    }
    client.request('POST', '/pods', json_body=body)


def _ssh_endpoint(pod: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Public (ip, port) for the pod's 22/tcp mapping.

    The REST surface returns `portMappings` as an object keyed by
    private port ({"22": 10341}) with the address in `publicIp`; the
    GraphQL-era shape is a list of dicts under runtime.ports. Handle
    both, and skip mappings whose public port isn't assigned yet.
    """
    mappings = pod.get('portMappings')
    if isinstance(mappings, dict):
        public = mappings.get('22')
        if public:
            return {'ip': pod.get('publicIp'), 'port': int(public)}
    elif isinstance(mappings, list):
        for mapping in mappings:
            if str(mapping.get('privatePort')) == '22' and \
                    mapping.get('publicPort'):
                return {'ip': mapping.get('ip') or pod.get('publicIp'),
                        'port': int(mapping['publicPort'])}
    for mapping in pod.get('runtime', {}).get('ports') or []:
        if str(mapping.get('privatePort')) == '22' and \
                mapping.get('isIpPublic', True) and \
                mapping.get('publicPort'):
            return {'ip': mapping.get('ip'),
                    'port': int(mapping['publicPort'])}
    return None


def _host_info(pod: Dict[str, Any]) -> common.HostInfo:
    endpoint = _ssh_endpoint(pod) or {}
    internal = pod.get('internalIp') or endpoint.get('ip') or ''
    return common.HostInfo(host_id=pod['id'], internal_ip=internal,
                           external_ip=endpoint.get('ip'),
                           ssh_port=endpoint.get('port', 22))


_SPEC = rest_driver.RestVmSpec(
    provider='runpod',
    adaptor=runpod_adaptor,
    ssh_user='root',
    list_instances=lambda client, ctx: _cluster_pods(client, ctx.cluster),
    state=_status,
    name_of=lambda pod: pod['name'],
    create=_create,
    host_info=_host_info,
    terminate=lambda client, ctx, pod: client.request(
        'DELETE', f'/pods/{pod["id"]}'),
    stop=lambda client, ctx, pod: client.request(
        'POST', f'/pods/{pod["id"]}/stop'),
    resume=lambda client, ctx, pod: client.request(
        'POST', f'/pods/{pod["id"]}/start'),
)

rest_driver.RestVmDriver(_SPEC).export(globals())
