"""SSH 'cloud': bring-your-own machines (on-prem TPU VMs, dev boxes).

Reference analog: sky/provision/ssh + the `ssh` cloud (node pools
declared in config; no create/terminate — machines already exist).
Config shape (~/.skytpu/config.yaml):

    ssh:
      node_pools:
        my-pool:
          user: ubuntu
          identity_file: ~/.ssh/id_ed25519
          hosts:
            - 10.0.0.1
            - host2.example.com
"""
import json
import os
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.provision import common
from skypilot_tpu.utils import command_runner
from skypilot_tpu.utils import paths


def _pool_config(pool: str) -> Dict[str, Any]:
    from skypilot_tpu import config as config_lib
    pools = config_lib.get_nested(('ssh', 'node_pools'), {}) or {}
    if pool not in pools:
        raise exceptions.ProvisionError(
            f'ssh: node pool {pool!r} not in config '
            f'(have: {sorted(pools)})')
    return pools[pool]


def _assignments_path() -> str:
    d = os.path.join(paths.state_dir(), 'ssh_assignments')
    os.makedirs(d, exist_ok=True)
    return d


def _assignment_file(cluster_name_on_cloud: str) -> str:
    return os.path.join(_assignments_path(),
                        f'{cluster_name_on_cloud}.json')


def _load_assignment(cluster_name_on_cloud: str) -> Optional[Dict]:
    try:
        with open(_assignment_file(cluster_name_on_cloud),
                  encoding='utf-8') as f:
            return json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return None


def _used_hosts(pool: str) -> List[str]:
    used: List[str] = []
    for fn in os.listdir(_assignments_path()):
        try:
            with open(os.path.join(_assignments_path(), fn),
                      encoding='utf-8') as f:
                a = json.load(f)
        except (json.JSONDecodeError, OSError):
            continue
        if a.get('pool') == pool:
            used.extend(a.get('hosts', []))
    return used


def run_instances(region: str, cluster_name_on_cloud: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    """'Provision' = reserve N free hosts from the pool.

    The reserve-and-write section is file-locked: concurrent launches
    from forked API workers must never double-assign a host.
    """
    import filelock
    pool = region
    lock = filelock.FileLock(
        os.path.join(_assignments_path(), '.reserve.lock'))
    with lock:
        existing = _load_assignment(cluster_name_on_cloud)
        if existing is not None:
            hosts = existing['hosts']
            if len(hosts) != config.count:
                raise exceptions.ProvisionError(
                    f'ssh cluster {cluster_name_on_cloud!r} already has '
                    f'{len(hosts)} host(s) reserved but {config.count} '
                    'were requested; tear it down first.')
        else:
            pool_cfg = _pool_config(pool)
            all_hosts = [str(h) for h in pool_cfg.get('hosts', [])]
            used = set(_used_hosts(pool))
            free = [h for h in all_hosts if h not in used]
            if len(free) < config.count:
                raise exceptions.CapacityError(
                    f'ssh pool {pool!r}: need {config.count} hosts, '
                    f'{len(free)} free of {len(all_hosts)}')
            hosts = free[:config.count]
            with open(_assignment_file(cluster_name_on_cloud), 'w',
                      encoding='utf-8') as f:
                json.dump({'pool': pool, 'hosts': hosts}, f)
    return common.ProvisionRecord(
        provider_name='ssh', region=pool, zone=None,
        cluster_name_on_cloud=cluster_name_on_cloud,
        head_instance_id=hosts[0],
        created_instance_ids=list(hosts))


def wait_instances(region: str, cluster_name_on_cloud: str,
                   state: Optional[str] = None) -> None:
    pass  # machines already exist


def stop_instances(cluster_name_on_cloud: str,
                   provider_config: Dict[str, Any]) -> None:
    raise exceptions.NotSupportedError(
        'ssh machines cannot be stopped by the framework.')


def terminate_instances(cluster_name_on_cloud: str,
                        provider_config: Dict[str, Any]) -> None:
    """Terminate = release the reservation (machines keep running)."""
    try:
        os.unlink(_assignment_file(cluster_name_on_cloud))
    except FileNotFoundError:
        pass


def query_instances(cluster_name_on_cloud: str,
                    provider_config: Dict[str, Any]
                    ) -> Dict[str, Optional[str]]:
    assignment = _load_assignment(cluster_name_on_cloud)
    if assignment is None:
        return {}
    return {h: 'running' for h in assignment['hosts']}


def get_cluster_info(region: str, cluster_name_on_cloud: str,
                     provider_config: Dict[str, Any]) -> common.ClusterInfo:
    assignment = _load_assignment(cluster_name_on_cloud)
    if assignment is None:
        return common.ClusterInfo(instances={}, head_instance_id=None,
                                  provider_name='ssh',
                                  provider_config=provider_config)
    pool_cfg = _pool_config(assignment['pool'])
    instances = {
        h: common.InstanceInfo(
            instance_id=h,
            hosts=[common.HostInfo(host_id=h, internal_ip=h,
                                   ssh_port=int(
                                       pool_cfg.get('port', 22)))],
            status='running')
        for h in assignment['hosts']
    }
    return common.ClusterInfo(
        instances=instances,
        head_instance_id=assignment['hosts'][0],
        provider_name='ssh',
        provider_config=provider_config,
        ssh_user=pool_cfg.get('user', os.environ.get('USER', 'root')),
        ssh_private_key=pool_cfg.get('identity_file'))


def open_ports(cluster_name_on_cloud: str, ports: List[str],
               provider_config: Dict[str, Any]) -> None:
    pass  # user-managed firewalls


def get_command_runners(cluster_info: common.ClusterInfo
                        ) -> List[command_runner.CommandRunner]:
    return [
        command_runner.SSHCommandRunner(
            host.internal_ip, user=cluster_info.ssh_user,
            private_key=cluster_info.ssh_private_key,
            port=host.ssh_port)
        for inst in cluster_info.ordered_instances()
        for host in inst.hosts
    ]
