"""Hyperbolic provisioner — GPU marketplace on the shared REST driver.

Reference analog: sky/provision/hyperbolic/instance.py + utils.py.
Like Vast, Hyperbolic is a market: `create-cheapest` accepts a GPU
shape and picks the cheapest live offer; an empty book is a
CapacityError so the failover engine moves on. Instances carry our
deterministic `<cluster>-<i>` identity in their metadata name;
terminate-only (no stop).
"""
import re
from typing import Any, Dict, List

from skypilot_tpu import exceptions
from skypilot_tpu.adaptors import hyperbolic as hyp_adaptor
from skypilot_tpu.provision import common, rest_driver

_STATE_MAP = {
    'creating': 'pending',
    'starting': 'pending',
    'provisioning': 'pending',
    'online': 'running',
    'ready': 'running',
    'stopping': 'stopping',
    'terminating': 'stopping',
    'offline': 'terminated',
    'terminated': 'terminated',
    'failed': 'terminated',
}


def _state(inst: Dict[str, Any]) -> str:
    return _STATE_MAP.get(str(inst.get('status', '')).lower(),
                          'pending')


def _name(inst: Dict[str, Any]) -> str:
    meta = inst.get('metadata') or {}
    return inst.get('name') or meta.get('name') or ''


def _list(client, ctx: rest_driver.Ctx) -> List[Dict[str, Any]]:
    pattern = re.compile(re.escape(ctx.cluster) + r'-\d+$')
    resp = client.request('GET', '/v1/marketplace/instances')
    return [i for i in resp.get('instances', [])
            if pattern.fullmatch(_name(i))]


def _create(client, ctx: rest_driver.Ctx, name: str) -> None:
    nc = ctx.nc
    resp = client.request(
        'POST', '/v2/marketplace/instances/create-cheapest',
        json_body={
            'gpu_model': nc.get('gpu_type', ''),
            'gpu_count': int(nc.get('gpu_count', 1)),
            'metadata': {'name': name},
            'ssh_public_key': common.require_public_key(
                ctx.config.authentication_config),
        })
    if not (resp.get('instance_id') or resp.get('id')):
        raise exceptions.CapacityError(
            f'Hyperbolic: no machine available for '
            f'{nc.get("gpu_type")}:{nc.get("gpu_count")}')


_SPEC = rest_driver.RestVmSpec(
    provider='hyperbolic',
    adaptor=hyp_adaptor,
    ssh_user='ubuntu',
    list_instances=_list,
    state=_state,
    name_of=_name,
    create=_create,
    host_info=lambda inst: common.HostInfo(
        host_id=str(inst['id']),
        internal_ip=inst.get('ip', ''),
        external_ip=inst.get('ip'),
        ssh_port=int(inst.get('ssh_port') or 22)),
    terminate=lambda client, ctx, inst: client.request(
        'POST', '/v1/marketplace/instances/terminate',
        json_body={'id': inst['id']}),
)

rest_driver.RestVmDriver(_SPEC).export(globals())
