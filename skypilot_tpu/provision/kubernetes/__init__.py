"""Kubernetes provisioner: pods-as-nodes via kubectl.

Reference analog: sky/provision/kubernetes/ (5.9k LoC; pods-as-nodes,
instance.py:1342). TPU-first cut: drives `kubectl` as a subprocess (no
python SDK dependency; the binary is ubiquitous and testable with a
fake), one pod per logical node, GKE TPU pod slices via
`google.com/tpu` resources + topology nodeSelectors.
"""
import json
import shlex
import subprocess
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.provision import common
from skypilot_tpu.utils import command_runner

CLUSTER_LABEL = 'skytpu-cluster'
HEAD_LABEL = 'skytpu-head'

_DEFAULT_IMAGE = 'python:3.11-slim'


def _kubectl(args: List[str], namespace: Optional[str] = None,
             input_data: Optional[str] = None) -> str:
    argv = ['kubectl']
    if namespace:
        argv += ['-n', namespace]
    argv += args
    proc = subprocess.run(argv, capture_output=True, text=True,
                          input=input_data, timeout=300, check=False)
    if proc.returncode != 0:
        raise exceptions.ProvisionError(
            f'kubectl {" ".join(args[:3])}... failed: '
            f'{proc.stderr.strip()}')
    return proc.stdout


def _pod_name(cluster_name_on_cloud: str, index: int) -> str:
    return f'{cluster_name_on_cloud}-{index}'


def _pod_manifest(config: common.ProvisionConfig, index: int,
                  cluster_name_on_cloud: str) -> Dict[str, Any]:
    pc = config.provider_config
    nc = {**pc, **config.node_config}
    name = _pod_name(cluster_name_on_cloud, index)
    resources: Dict[str, Any] = {}
    limits: Dict[str, Any] = {}
    if nc.get('cpus'):
        resources['cpu'] = str(nc['cpus'])
    if nc.get('memory'):
        resources['memory'] = f'{nc["memory"]}Gi'
    tpu_chips = nc.get('tpu_chips_per_node')
    node_selector: Dict[str, str] = dict(nc.get('node_selector', {}))
    if tpu_chips:
        # GKE TPU: request chips + pin accelerator/topology selectors.
        limits['google.com/tpu'] = str(tpu_chips)
        if nc.get('gke_accelerator'):
            node_selector['cloud.google.com/gke-tpu-accelerator'] = \
                nc['gke_accelerator']
        if nc.get('tpu_topology'):
            node_selector['cloud.google.com/gke-tpu-topology'] = \
                nc['tpu_topology']
    manifest = {
        'apiVersion': 'v1',
        'kind': 'Pod',
        'metadata': {
            'name': name,
            'labels': {
                CLUSTER_LABEL: cluster_name_on_cloud,
                HEAD_LABEL: 'true' if index == 0 else 'false',
                **nc.get('labels', {}),
            },
        },
        'spec': {
            'restartPolicy': 'Never',
            'containers': [{
                'name': 'main',
                'image': nc.get('image_id', _DEFAULT_IMAGE),
                'command': ['/bin/bash', '-c',
                            'sleep infinity'],
                'resources': ({'requests': resources,
                               'limits': {**resources, **limits}}
                              if resources or limits else {}),
            }],
        },
    }
    if node_selector:
        manifest['spec']['nodeSelector'] = node_selector
    return manifest


def _deployment_manifest(config: common.ProvisionConfig,
                         cluster_name_on_cloud: str) -> Dict[str, Any]:
    """HA controller host: a single-replica Deployment (Recreate) so
    kubernetes resurrects the pod on node/container failure; the
    recovery command re-primes the restarted pod (skylet restart +
    controller crash-resume) before the steady-state sleep.

    Reference analog: HIGH_AVAILABILITY_CONTROLLERS
    (sky/clouds/cloud.py:32) + the ha_recovery re-run script in
    sky/templates/kubernetes-ray.yml.j2.
    """
    nc = {**config.provider_config, **config.node_config}
    pod = _pod_manifest(config, 0, cluster_name_on_cloud)
    pod_spec = pod['spec']
    labels = pod['metadata']['labels']
    recovery = nc.get('recovery_command')
    if recovery:
        pod_spec['containers'][0]['command'] = [
            '/bin/bash', '-c', f'({recovery}); sleep infinity']
    # The Deployment owns restarts; the pod must not refuse them.
    pod_spec['restartPolicy'] = 'Always'
    return {
        'apiVersion': 'apps/v1',
        'kind': 'Deployment',
        'metadata': {
            'name': f'{cluster_name_on_cloud}-ha',
            'labels': dict(labels),
        },
        'spec': {
            'replicas': 1,
            # Never two controllers at once (duplicate schedulers
            # would double-launch jobs): kill-then-recreate.
            'strategy': {'type': 'Recreate'},
            'selector': {'matchLabels': {
                CLUSTER_LABEL: cluster_name_on_cloud}},
            'template': {'metadata': {'labels': dict(labels)},
                         'spec': pod_spec},
        },
    }


def run_instances(region: str, cluster_name_on_cloud: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    del region  # k8s "region" is the context/namespace
    namespace = config.provider_config.get('namespace', 'default')
    existing = query_instances(cluster_name_on_cloud,
                               config.provider_config)
    created: List[str] = []
    if config.provider_config.get('ha'):
        if config.count != 1:
            raise exceptions.ProvisionError(
                'HA (Deployment-backed) clusters are single-node '
                'controller hosts; got count='
                f'{config.count}.')
        if not any(s in ('running', 'pending')
                   for s in existing.values()):
            _kubectl(['apply', '-f', '-'], namespace=namespace,
                     input_data=json.dumps(_deployment_manifest(
                         config, cluster_name_on_cloud)))
            created.append(f'{cluster_name_on_cloud}-ha')
        return common.ProvisionRecord(
            provider_name='kubernetes',
            region=namespace, zone=None,
            cluster_name_on_cloud=cluster_name_on_cloud,
            head_instance_id=f'{cluster_name_on_cloud}-ha',
            created_instance_ids=created)
    for i in range(config.count):
        name = _pod_name(cluster_name_on_cloud, i)
        if existing.get(name) in ('running', 'pending'):
            continue
        manifest = _pod_manifest(config, i, cluster_name_on_cloud)
        _kubectl(['apply', '-f', '-'], namespace=namespace,
                 input_data=json.dumps(manifest))
        created.append(name)
    return common.ProvisionRecord(
        provider_name='kubernetes',
        region=namespace, zone=None,
        cluster_name_on_cloud=cluster_name_on_cloud,
        head_instance_id=_pod_name(cluster_name_on_cloud, 0),
        created_instance_ids=created)


def wait_instances(region: str, cluster_name_on_cloud: str,
                   state: Optional[str] = None) -> None:
    import time
    deadline = time.time() + 600
    while time.time() < deadline:
        statuses = query_instances(cluster_name_on_cloud,
                                   {'namespace': region})
        if statuses and all(s == 'running' for s in statuses.values()):
            return
        if any(s == 'terminated' for s in statuses.values()):
            raise exceptions.CapacityError(
                f'Pod(s) failed: {statuses}')
        time.sleep(2)
    raise exceptions.ProvisionError(
        f'Pods not running after 600s: {cluster_name_on_cloud}')


def _list_pods(cluster_name_on_cloud: str,
               namespace: str) -> List[Dict[str, Any]]:
    out = _kubectl(['get', 'pods', '-l',
                    f'{CLUSTER_LABEL}={cluster_name_on_cloud}',
                    '-o', 'json'], namespace=namespace)
    return json.loads(out).get('items', [])


_PHASE_MAP = {
    'Pending': 'pending',
    'Running': 'running',
    'Succeeded': 'terminated',
    'Failed': 'terminated',
    'Unknown': 'pending',
}


def query_instances(cluster_name_on_cloud: str,
                    provider_config: Dict[str, Any]
                    ) -> Dict[str, Optional[str]]:
    namespace = provider_config.get('namespace', 'default')
    out: Dict[str, Optional[str]] = {}
    for pod in _list_pods(cluster_name_on_cloud, namespace):
        phase = pod.get('status', {}).get('phase', 'Unknown')
        out[pod['metadata']['name']] = _PHASE_MAP.get(phase, 'pending')
    return out


def stop_instances(cluster_name_on_cloud: str,
                   provider_config: Dict[str, Any]) -> None:
    raise exceptions.NotSupportedError(
        'Kubernetes pods cannot stop; terminate instead.')


def terminate_instances(cluster_name_on_cloud: str,
                        provider_config: Dict[str, Any]) -> None:
    namespace = provider_config.get('namespace', 'default')
    if provider_config.get('ha'):
        # Deployment first or it would just heal the deleted pods.
        _kubectl(['delete', 'deployments', '-l',
                  f'{CLUSTER_LABEL}={cluster_name_on_cloud}',
                  '--ignore-not-found=true', '--wait=false'],
                 namespace=namespace)
    _kubectl(['delete', 'pods', '-l',
              f'{CLUSTER_LABEL}={cluster_name_on_cloud}',
              '--ignore-not-found=true', '--wait=false'],
             namespace=namespace)


def get_cluster_info(region: str, cluster_name_on_cloud: str,
                     provider_config: Dict[str, Any]) -> common.ClusterInfo:
    del region
    namespace = provider_config.get('namespace', 'default')
    instances: Dict[str, common.InstanceInfo] = {}
    head_id: Optional[str] = None
    for pod in _list_pods(cluster_name_on_cloud, namespace):
        if pod.get('status', {}).get('phase') != 'Running':
            continue
        name = pod['metadata']['name']
        instances[name] = common.InstanceInfo(
            instance_id=name,
            hosts=[common.HostInfo(
                host_id=name,
                internal_ip=pod.get('status', {}).get('podIP', ''))],
            status='running',
            tags=dict(pod['metadata'].get('labels', {})))
        if pod['metadata'].get('labels', {}).get(HEAD_LABEL) == 'true':
            head_id = name
    if head_id is None and instances:
        head_id = sorted(instances)[0]
    return common.ClusterInfo(
        instances=instances, head_instance_id=head_id,
        provider_name='kubernetes',
        provider_config=provider_config,
        ssh_user='root')


def open_ports(cluster_name_on_cloud: str, ports: List[str],
               provider_config: Dict[str, Any]) -> None:
    """Expose ports with a Service per cluster."""
    namespace = provider_config.get('namespace', 'default')
    manifest = {
        'apiVersion': 'v1',
        'kind': 'Service',
        'metadata': {'name': f'{cluster_name_on_cloud}-svc',
                     'labels': {CLUSTER_LABEL: cluster_name_on_cloud}},
        'spec': {
            'selector': {CLUSTER_LABEL: cluster_name_on_cloud,
                         HEAD_LABEL: 'true'},
            'ports': [{'name': f'p{p}', 'port': int(p),
                       'targetPort': int(p)} for p in ports],
            'type': provider_config.get('service_type', 'ClusterIP'),
        },
    }
    _kubectl(['apply', '-f', '-'], namespace=namespace,
             input_data=json.dumps(manifest))


def get_command_runners(cluster_info: common.ClusterInfo
                        ) -> List[command_runner.CommandRunner]:
    namespace = cluster_info.provider_config.get('namespace', 'default')
    return [
        command_runner.KubernetesCommandRunner(inst.instance_id,
                                               namespace=namespace)
        for inst in cluster_info.ordered_instances()
    ]
