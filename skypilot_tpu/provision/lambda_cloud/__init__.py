"""Lambda Cloud provisioner — GPU neocloud behind the uniform interface.

Reference analog: sky/provision/lambda_cloud/instance.py. The API is
launch/list/terminate only (no stop, no custom images, no port
controls): instances are identified by the `name` we assign
(`<cluster>-<i>`), and all firewalling is account-global. Autostop
therefore forces `--down`, the same gate the backend already applies
to TPU pods.

SSH keys: Lambda injects a *named* account-level key at launch; we
idempotently register the cluster keypair under a deterministic name
derived from the public key fingerprint.
"""
import hashlib
import logging
import re
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.adaptors import lambda_cloud as lambda_adaptor
from skypilot_tpu.provision import common

logger = logging.getLogger(__name__)

_STATE_MAP = {
    'booting': 'pending',
    'active': 'running',
    'unhealthy': 'running',
    'terminating': 'stopping',
    'terminated': 'terminated',
}


def _cluster_instances(client, cluster_name_on_cloud: str
                       ) -> List[Dict[str, Any]]:
    resp = client.request('GET', '/instances')
    # Exact `<cluster>-<index>` match: a bare prefix would also catch
    # cluster 'train-2' when tearing down cluster 'train'.
    pattern = re.compile(re.escape(cluster_name_on_cloud) + r'-\d+$')
    return [inst for inst in resp.get('data', [])
            if pattern.fullmatch(inst.get('name') or '')]


def _state(inst: Dict[str, Any]) -> str:
    return _STATE_MAP.get(inst.get('status', ''), 'pending')


def _ensure_ssh_key(client, public_key: str) -> str:
    """Idempotently register the cluster public key; returns its name."""
    digest = hashlib.sha256(public_key.encode()).hexdigest()[:12]
    key_name = f'skytpu-{digest}'
    existing = client.request('GET', '/ssh-keys')
    for key in existing.get('data', []):
        if key.get('name') == key_name:
            return key_name
    client.request('POST', '/ssh-keys',
                   json_body={'name': key_name,
                              'public_key': public_key})
    return key_name


def run_instances(region: str, cluster_name_on_cloud: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    client = lambda_adaptor.client()
    nc = {**config.provider_config, **config.node_config}
    existing = _cluster_instances(client, cluster_name_on_cloud)
    # Duplicate names can coexist briefly (e.g. a terminating twin
    # alongside its replacement), so classify per-name over ALL
    # same-name instances rather than last-listed-wins.
    alive = {inst['name'] for inst in existing
             if _state(inst) in ('running', 'pending')}
    stopping = {inst['name'] for inst in existing
                if _state(inst) == 'stopping'} - alive

    created: List[str] = []
    try:
        key_name = _ensure_ssh_key(
            client,
            common.require_public_key(config.authentication_config))
        for i in range(config.count):
            name = f'{cluster_name_on_cloud}-{i}'
            if name in alive:
                continue
            if name in stopping:
                common.refuse_unresumable('stopping', name)
            resp = client.request(
                'POST', '/instance-operations/launch',
                json_body={
                    'region_name': region,
                    'instance_type_name': nc['instance_type'],
                    'ssh_key_names': [key_name],
                    'quantity': 1,
                    'name': name,
                })
            ids = resp.get('data', {}).get('instance_ids', [])
            if not ids:
                raise exceptions.ProvisionError(
                    f'Lambda launch returned no instance id for {name}')
            created.append(name)
        _wait_active(client, cluster_name_on_cloud, config.count,
                     timeout=float(config.provider_config.get(
                         'provision_timeout', 900)))
    except lambda_adaptor.RestApiError as e:
        raise lambda_adaptor.classify_api_error(e) from e
    return common.ProvisionRecord(
        provider_name='lambda', region=region, zone=None,
        cluster_name_on_cloud=cluster_name_on_cloud,
        head_instance_id=f'{cluster_name_on_cloud}-0',
        created_instance_ids=created, resumed_instance_ids=[])


def _wait_active(client, cluster_name_on_cloud: str, count: int,
                 timeout: float = 900.0) -> None:
    common.wait_until_running(
        lambda: _cluster_instances(client, cluster_name_on_cloud),
        count, _state, lambda i: i['name'], timeout=timeout)


def wait_instances(region: str, cluster_name_on_cloud: str,
                   state: Optional[str] = None) -> None:
    del region, cluster_name_on_cloud, state  # run_instances waits


def stop_instances(cluster_name_on_cloud: str,
                   provider_config: Dict[str, Any]) -> None:
    raise exceptions.NotSupportedError(
        'Lambda Cloud cannot stop instances; use terminate (down).')


def terminate_instances(cluster_name_on_cloud: str,
                        provider_config: Dict[str, Any]) -> None:
    client = lambda_adaptor.client()
    ids = [inst['id']
           for inst in _cluster_instances(client, cluster_name_on_cloud)
           if _state(inst) not in ('terminated', 'stopping')]
    if not ids:
        return
    client.request('POST', '/instance-operations/terminate',
                   json_body={'instance_ids': ids})


def query_instances(cluster_name_on_cloud: str,
                    provider_config: Dict[str, Any]
                    ) -> Dict[str, Optional[str]]:
    client = lambda_adaptor.client()
    out: Dict[str, Optional[str]] = {}
    for inst in _cluster_instances(client, cluster_name_on_cloud):
        state = _state(inst)
        if state == 'terminated':
            continue
        out[inst['name']] = state
    return out


def get_cluster_info(region: str, cluster_name_on_cloud: str,
                     provider_config: Dict[str, Any]) -> common.ClusterInfo:
    del region
    client = lambda_adaptor.client()
    instances: Dict[str, common.InstanceInfo] = {}
    head_id: Optional[str] = None
    head_name = f'{cluster_name_on_cloud}-0'
    for inst in _cluster_instances(client, cluster_name_on_cloud):
        if _state(inst) != 'running':
            continue
        name = inst['name']
        instances[name] = common.InstanceInfo(
            instance_id=name,
            hosts=[common.HostInfo(host_id=inst['id'],
                                   internal_ip=inst.get('private_ip', ''),
                                   external_ip=inst.get('ip'))],
            status='running', tags={})
        if name == head_name:
            head_id = name
    if head_id is None and instances:
        head_id = sorted(instances)[0]
    return common.ClusterInfo(
        instances=instances, head_instance_id=head_id,
        provider_name='lambda', provider_config=provider_config,
        ssh_user='ubuntu',
        ssh_private_key=provider_config.get('ssh_private_key'))


def get_command_runners(cluster_info: common.ClusterInfo):
    return common.ssh_command_runners(cluster_info, 'ubuntu')
