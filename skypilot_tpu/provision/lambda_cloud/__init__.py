"""Lambda Cloud provisioner — GPU neocloud on the shared REST driver.

Reference analog: sky/provision/lambda_cloud/instance.py. The API is
launch/list/terminate only (no stop, no custom images, no port
controls): instances are identified by the `name` we assign
(`<cluster>-<i>`), and all firewalling is account-global. Autostop
therefore forces `--down`, the same gate the backend already applies
to TPU pods.

SSH keys: Lambda injects a *named* account-level key at launch; we
idempotently register the cluster keypair under a deterministic name
derived from the public key fingerprint.
"""
import hashlib
import re
from typing import Any, Dict, List

from skypilot_tpu import exceptions
from skypilot_tpu.adaptors import lambda_cloud as lambda_adaptor
from skypilot_tpu.provision import common, rest_driver

_STATE_MAP = {
    'booting': 'pending',
    'active': 'running',
    'unhealthy': 'running',
    'terminating': 'stopping',
    'terminated': 'terminated',
}


def _cluster_instances(client, cluster_name_on_cloud: str
                       ) -> List[Dict[str, Any]]:
    resp = client.request('GET', '/instances')
    # Exact `<cluster>-<index>` match: a bare prefix would also catch
    # cluster 'train-2' when tearing down cluster 'train'.
    pattern = re.compile(re.escape(cluster_name_on_cloud) + r'-\d+$')
    return [inst for inst in resp.get('data', [])
            if pattern.fullmatch(inst.get('name') or '')]


def _state(inst: Dict[str, Any]) -> str:
    return _STATE_MAP.get(inst.get('status', ''), 'pending')


def _ensure_ssh_key(client, ctx: rest_driver.Ctx) -> None:
    """Idempotently register the cluster public key under a
    fingerprint-derived name; stashes the name for _create."""
    public_key = common.require_public_key(
        ctx.config.authentication_config)
    digest = hashlib.sha256(public_key.encode()).hexdigest()[:12]
    key_name = f'skytpu-{digest}'
    existing = client.request('GET', '/ssh-keys')
    if not any(key.get('name') == key_name
               for key in existing.get('data', [])):
        client.request('POST', '/ssh-keys',
                       json_body={'name': key_name,
                                  'public_key': public_key})
    ctx.data['key_name'] = key_name


def _create(client, ctx: rest_driver.Ctx, name: str) -> None:
    resp = client.request(
        'POST', '/instance-operations/launch',
        json_body={
            'region_name': ctx.region,
            'instance_type_name': ctx.nc['instance_type'],
            'ssh_key_names': [ctx.data['key_name']],
            'quantity': 1,
            'name': name,
        })
    if not resp.get('data', {}).get('instance_ids', []):
        raise exceptions.ProvisionError(
            f'Lambda launch returned no instance id for {name}')


def _terminate_all(client, ctx: rest_driver.Ctx) -> None:
    ids = [inst['id']
           for inst in _cluster_instances(client, ctx.cluster)
           if _state(inst) not in ('terminated', 'stopping')]
    if ids:
        client.request('POST', '/instance-operations/terminate',
                       json_body={'instance_ids': ids})


_SPEC = rest_driver.RestVmSpec(
    provider='lambda',
    adaptor=lambda_adaptor,
    ssh_user='ubuntu',
    list_instances=lambda client, ctx: _cluster_instances(client,
                                                          ctx.cluster),
    state=_state,
    name_of=lambda inst: inst['name'],
    create=_create,
    host_info=lambda inst: common.HostInfo(
        host_id=inst['id'],
        internal_ip=inst.get('private_ip', ''),
        external_ip=inst.get('ip')),
    terminate_all=_terminate_all,
    # No stop/resume: Lambda has no stopped state at all.
    prepare_launch=_ensure_ssh_key,
)

rest_driver.RestVmDriver(_SPEC).export(globals())
