"""DigitalOcean provisioner — droplets behind the uniform interface.

Reference analog: sky/provision/do/instance.py. Droplets are tagged
`skytpu:<cluster>` (tags are DO's native grouping primitive) and named
`<cluster>-<i>`. The cluster SSH key is idempotently registered under
a fingerprint-derived name; power_off/power_on give real stop/resume
(disk persists, billing drops to disk-only).
"""
import hashlib
import logging
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.adaptors import do as do_adaptor
from skypilot_tpu.provision import common

logger = logging.getLogger(__name__)

_DEFAULT_IMAGE = 'ubuntu-22-04-x64'


def _tag(cluster_name_on_cloud: str) -> str:
    # DO tags allow letters/digits/:/-/_ .
    return f'skytpu:{cluster_name_on_cloud}'


def _droplet_state(droplet: Dict[str, Any]) -> str:
    status = droplet.get('status', 'new')
    return {'new': 'pending', 'active': 'running', 'off': 'stopped',
            'archive': 'terminated'}.get(status, 'pending')


def _cluster_droplets(client, cluster_name_on_cloud: str,
                      region: Optional[str] = None
                      ) -> List[Dict[str, Any]]:
    """Tag-matched droplets; `region` narrows to one region so a
    failover retry elsewhere never adopts a dying droplet from the
    failed region (teardown/query stay region-global)."""
    resp = client.request(
        'GET', '/v2/droplets',
        params={'tag_name': _tag(cluster_name_on_cloud),
                'per_page': '200'})
    droplets = resp.get('droplets', [])
    if region is not None:
        droplets = [d for d in droplets
                    if (d.get('region') or {}).get('slug') == region]
    return droplets


def _key_body(public_key: str) -> str:
    """Comparable core of an authorized_keys line (type + base64 body,
    comment dropped — DO rewrites comments)."""
    return ' '.join(public_key.split()[:2])


def _find_key_id(client, public_key: str) -> Optional[int]:
    """Scan ALL account keys (paginated) for this public key,
    regardless of the name it was registered under — DO rejects
    duplicate fingerprints, so a key the user added via the web UI
    must be reused, not re-POSTed."""
    body = _key_body(public_key)
    page = 1
    while True:
        resp = client.request('GET', '/v2/account/keys',
                              params={'per_page': '200',
                                      'page': str(page)})
        keys = resp.get('ssh_keys', [])
        for key in keys:
            if _key_body(key.get('public_key', '')) == body:
                return key['id']
        if len(keys) < 200:
            return None
        page += 1


def _ensure_ssh_key(client, public_key: str) -> int:
    """Idempotently register the cluster public key; returns its id."""
    key_id = _find_key_id(client, public_key)
    if key_id is not None:
        return key_id
    digest = hashlib.sha256(public_key.encode()).hexdigest()[:12]
    try:
        created = client.request('POST', '/v2/account/keys',
                                 json_body={'name': f'skytpu-{digest}',
                                            'public_key': public_key})
    except do_adaptor.RestApiError as e:
        if e.status == 422:  # raced: registered since our scan
            key_id = _find_key_id(client, public_key)
            if key_id is not None:
                return key_id
        raise
    return created['ssh_key']['id']


def run_instances(region: str, cluster_name_on_cloud: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    client = do_adaptor.client()
    nc = {**config.provider_config, **config.node_config}
    existing = {d['name']: d
                for d in _cluster_droplets(client, cluster_name_on_cloud,
                                           region=region)}
    created: List[str] = []
    resumed: List[str] = []
    try:
        key_id = _ensure_ssh_key(
            client,
            common.require_public_key(config.authentication_config))
        for i in range(config.count):
            name = f'{cluster_name_on_cloud}-{i}'
            droplet = existing.get(name)
            state = _droplet_state(droplet) if droplet else None
            if state in ('running', 'pending'):
                continue
            if state == 'stopped':
                if not config.resume_stopped_nodes:
                    raise exceptions.ProvisionError(
                        f'Droplet {name} is stopped; pass '
                        'resume_stopped_nodes to restart it.')
                client.request(
                    'POST', f'/v2/droplets/{droplet["id"]}/actions',
                    json_body={'type': 'power_on'})
                resumed.append(name)
                continue
            body = {
                'name': name,
                'region': region,
                'size': nc['instance_type'],
                'image': nc.get('image_id') or _DEFAULT_IMAGE,
                'ssh_keys': [key_id],
                'tags': [_tag(cluster_name_on_cloud)],
                'monitoring': False,
            }
            client.request('POST', '/v2/droplets', json_body=body)
            created.append(name)
        _wait_active(client, cluster_name_on_cloud, config.count,
                     region=region,
                     timeout=float(config.provider_config.get(
                         'provision_timeout', 900)))
    except do_adaptor.RestApiError as e:
        raise do_adaptor.classify_api_error(e) from e
    return common.ProvisionRecord(
        provider_name='do', region=region, zone=None,
        cluster_name_on_cloud=cluster_name_on_cloud,
        head_instance_id=f'{cluster_name_on_cloud}-0',
        created_instance_ids=created, resumed_instance_ids=resumed)


def _wait_active(client, cluster_name_on_cloud: str, count: int,
                 region: Optional[str] = None,
                 timeout: float = 900.0) -> None:
    common.wait_until_running(
        lambda: _cluster_droplets(client, cluster_name_on_cloud,
                                  region=region),
        count, _droplet_state, lambda d: d['name'], timeout=timeout)


def wait_instances(region: str, cluster_name_on_cloud: str,
                   state: Optional[str] = None) -> None:
    del region, cluster_name_on_cloud, state  # run_instances waits


def stop_instances(cluster_name_on_cloud: str,
                   provider_config: Dict[str, Any]) -> None:
    client = do_adaptor.client()
    for droplet in _cluster_droplets(client, cluster_name_on_cloud):
        if _droplet_state(droplet) == 'running':
            client.request('POST',
                           f'/v2/droplets/{droplet["id"]}/actions',
                           json_body={'type': 'power_off'})


def terminate_instances(cluster_name_on_cloud: str,
                        provider_config: Dict[str, Any]) -> None:
    client = do_adaptor.client()
    try:
        client.request(
            'DELETE', '/v2/droplets',
            params={'tag_name': _tag(cluster_name_on_cloud)})
    except do_adaptor.RestApiError as e:
        if e.status != 404:
            raise


def query_instances(cluster_name_on_cloud: str,
                    provider_config: Dict[str, Any]
                    ) -> Dict[str, Optional[str]]:
    client = do_adaptor.client()
    out: Dict[str, Optional[str]] = {}
    # Scope to the handle's region when known: names collide across
    # regions after a failover, and a dying other-region droplet must
    # not shadow the real node's status.
    for droplet in _cluster_droplets(client, cluster_name_on_cloud,
                                     region=provider_config.get('region')):
        state = _droplet_state(droplet)
        if state == 'terminated':
            continue
        out[droplet['name']] = state
    return out


def _ips(droplet: Dict[str, Any]) -> Dict[str, Optional[str]]:
    internal, external = '', None
    for net in droplet.get('networks', {}).get('v4', []):
        if net.get('type') == 'private':
            internal = net.get('ip_address', '')
        elif net.get('type') == 'public':
            external = net.get('ip_address')
    return {'internal': internal or (external or ''),
            'external': external}


def get_cluster_info(region: str, cluster_name_on_cloud: str,
                     provider_config: Dict[str, Any]) -> common.ClusterInfo:
    client = do_adaptor.client()
    instances: Dict[str, common.InstanceInfo] = {}
    head_name = f'{cluster_name_on_cloud}-0'
    head_id: Optional[str] = None
    # Region-scoped: a same-name droplet lingering in a failed-over
    # region must not supply the head IP.
    for droplet in _cluster_droplets(client, cluster_name_on_cloud,
                                     region=region):
        if _droplet_state(droplet) != 'running':
            continue
        name = droplet['name']
        ips = _ips(droplet)
        instances[name] = common.InstanceInfo(
            instance_id=name,
            hosts=[common.HostInfo(host_id=str(droplet['id']),
                                   internal_ip=ips['internal'],
                                   external_ip=ips['external'])],
            status='running', tags={})
        if name == head_name:
            head_id = name
    if head_id is None and instances:
        head_id = sorted(instances)[0]
    return common.ClusterInfo(
        instances=instances, head_instance_id=head_id,
        provider_name='do', provider_config=provider_config,
        ssh_user='root',
        ssh_private_key=provider_config.get('ssh_private_key'))


def get_command_runners(cluster_info: common.ClusterInfo):
    return common.ssh_command_runners(cluster_info, 'root')
