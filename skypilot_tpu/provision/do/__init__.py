"""DigitalOcean provisioner — droplets on the shared REST driver.

Reference analog: sky/provision/do/instance.py. Droplets are tagged
`skytpu:<cluster>` (tags are DO's native grouping primitive) and named
`<cluster>-<i>`. The cluster SSH key is idempotently registered under
a fingerprint-derived name; power_off/power_on give real stop/resume
(disk persists, billing drops to disk-only).
"""
import hashlib
from typing import Any, Dict, List, Optional

from skypilot_tpu.adaptors import do as do_adaptor
from skypilot_tpu.provision import common, rest_driver

_DEFAULT_IMAGE = 'ubuntu-22-04-x64'


def _tag(cluster_name_on_cloud: str) -> str:
    # DO tags allow letters/digits/:/-/_ .
    return f'skytpu:{cluster_name_on_cloud}'


def _droplet_state(droplet: Dict[str, Any]) -> str:
    status = droplet.get('status', 'new')
    return {'new': 'pending', 'active': 'running', 'off': 'stopped',
            'archive': 'terminated'}.get(status, 'pending')


def _list(client, ctx: rest_driver.Ctx) -> List[Dict[str, Any]]:
    """Tag-matched droplets; ctx.region (set for launch/query/info,
    None for stop/terminate) narrows to one region so a failover retry
    elsewhere never adopts a dying droplet from the failed region."""
    resp = client.request(
        'GET', '/v2/droplets',
        params={'tag_name': _tag(ctx.cluster), 'per_page': '200'})
    droplets = resp.get('droplets', [])
    if ctx.region is not None:
        droplets = [d for d in droplets
                    if (d.get('region') or {}).get('slug') == ctx.region]
    return droplets


def _key_body(public_key: str) -> str:
    """Comparable core of an authorized_keys line (type + base64 body,
    comment dropped — DO rewrites comments)."""
    return ' '.join(public_key.split()[:2])


def _find_key_id(client, public_key: str) -> Optional[int]:
    """Scan ALL account keys (paginated) for this public key,
    regardless of the name it was registered under — DO rejects
    duplicate fingerprints, so a key the user added via the web UI
    must be reused, not re-POSTed."""
    body = _key_body(public_key)
    page = 1
    while True:
        resp = client.request('GET', '/v2/account/keys',
                              params={'per_page': '200',
                                      'page': str(page)})
        keys = resp.get('ssh_keys', [])
        for key in keys:
            if _key_body(key.get('public_key', '')) == body:
                return key['id']
        if len(keys) < 200:
            return None
        page += 1


def _ensure_ssh_key(client, ctx: rest_driver.Ctx) -> None:
    """Idempotently register the cluster public key; stashes its id."""
    public_key = common.require_public_key(
        ctx.config.authentication_config)
    key_id = _find_key_id(client, public_key)
    if key_id is None:
        digest = hashlib.sha256(public_key.encode()).hexdigest()[:12]
        try:
            created = client.request(
                'POST', '/v2/account/keys',
                json_body={'name': f'skytpu-{digest}',
                           'public_key': public_key})
            key_id = created['ssh_key']['id']
        except do_adaptor.RestApiError as e:
            if e.status != 422:  # 422 = raced: registered since scan
                raise
            key_id = _find_key_id(client, public_key)
            if key_id is None:
                raise
    ctx.data['key_id'] = key_id


def _create(client, ctx: rest_driver.Ctx, name: str) -> None:
    nc = ctx.nc
    body = {
        'name': name,
        'region': ctx.region,
        'size': nc['instance_type'],
        'image': nc.get('image_id') or _DEFAULT_IMAGE,
        'ssh_keys': [ctx.data['key_id']],
        'tags': [_tag(ctx.cluster)],
        'monitoring': False,
    }
    client.request('POST', '/v2/droplets', json_body=body)


def _terminate_all(client, ctx: rest_driver.Ctx) -> None:
    try:
        client.request('DELETE', '/v2/droplets',
                       params={'tag_name': _tag(ctx.cluster)})
    except do_adaptor.RestApiError as e:
        if e.status != 404:
            raise


def _host_info(droplet: Dict[str, Any]) -> common.HostInfo:
    internal, external = '', None
    for net in droplet.get('networks', {}).get('v4', []):
        if net.get('type') == 'private':
            internal = net.get('ip_address', '')
        elif net.get('type') == 'public':
            external = net.get('ip_address')
    return common.HostInfo(host_id=str(droplet['id']),
                           internal_ip=internal or (external or ''),
                           external_ip=external)


_SPEC = rest_driver.RestVmSpec(
    provider='do',
    adaptor=do_adaptor,
    ssh_user='root',
    list_instances=_list,
    state=_droplet_state,
    name_of=lambda d: d['name'],
    create=_create,
    host_info=_host_info,
    terminate_all=_terminate_all,
    stop=lambda client, ctx, d: client.request(
        'POST', f'/v2/droplets/{d["id"]}/actions',
        json_body={'type': 'power_off'}),
    resume=lambda client, ctx, d: client.request(
        'POST', f'/v2/droplets/{d["id"]}/actions',
        json_body={'type': 'power_on'}),
    prepare_launch=_ensure_ssh_key,
)

rest_driver.RestVmDriver(_SPEC).export(globals())
