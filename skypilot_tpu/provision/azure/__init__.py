"""Azure VM provisioner — third VM cloud behind the uniform interface.

Reference analog: sky/provision/azure/ (1301 LoC, azure SDK). Ours
drives ARM REST through the injectable adaptor client. Azure-first
simplification: every cluster lives in its own resource group
(`skytpu-<cluster>`), so terminate is a single resource-group delete
and nothing can leak. VM/NIC/IP names are deterministic per node
index; SSH keys ride osProfile.linuxConfiguration (no agent needed).
"""
import logging
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.adaptors import azure as azure_adaptor
from skypilot_tpu.provision import common

logger = logging.getLogger(__name__)

CLUSTER_TAG = 'skytpu-cluster'
HEAD_TAG = 'skytpu-head'
INDEX_TAG = 'skytpu-index'

_RG_API = '2021-04-01'
_DEFAULT_IMAGE = {
    'publisher': 'Canonical',
    'offer': '0001-com-ubuntu-server-jammy',
    'sku': '22_04-lts-gen2',
    'version': 'latest',
}

_POWER_MAP = {
    'PowerState/running': 'running',
    'PowerState/starting': 'pending',
    'PowerState/stopping': 'stopping',
    'PowerState/stopped': 'stopped',
    'PowerState/deallocating': 'stopping',
    'PowerState/deallocated': 'stopped',
}


def _sub(pc: Dict[str, Any]) -> str:
    sub = pc.get('subscription_id')
    if not sub:
        sub = azure_adaptor.default_subscription()
        pc['subscription_id'] = sub
    return sub


def _rg(cluster_name_on_cloud: str) -> str:
    return f'skytpu-{cluster_name_on_cloud}'


def _rg_path(sub: str, rg: str) -> str:
    return f'/subscriptions/{sub}/resourceGroups/{rg}'


def _compute(sub: str, rg: str, kind: str, name: str = '') -> str:
    base = (f'{_rg_path(sub, rg)}/providers/Microsoft.Compute/{kind}')
    return f'{base}/{name}' if name else base


def _network(sub: str, rg: str, kind: str, name: str = '') -> str:
    base = (f'{_rg_path(sub, rg)}/providers/Microsoft.Network/{kind}')
    return f'{base}/{name}' if name else base


def _cparams() -> Dict[str, str]:
    return {'api-version': azure_adaptor.COMPUTE_API_VERSION}


def _nparams() -> Dict[str, str]:
    return {'api-version': azure_adaptor.NETWORK_API_VERSION}


def _ensure_network(client, sub: str, rg: str, region: str) -> None:
    """VNet + subnet + SSH-open NSG, idempotent PUTs."""
    client.request('PUT', _network(sub, rg, 'networkSecurityGroups',
                                   'skytpu-nsg'),
                   params=_nparams(), json_body={
        'location': region,
        'properties': {'securityRules': [{
            'name': 'ssh',
            'properties': {
                'priority': 1000, 'direction': 'Inbound',
                'access': 'Allow', 'protocol': 'Tcp',
                'sourceAddressPrefix': '*', 'sourcePortRange': '*',
                'destinationAddressPrefix': '*',
                'destinationPortRange': '22',
            }}]},
    })
    client.request('PUT', _network(sub, rg, 'virtualNetworks',
                                   'skytpu-vnet'),
                   params=_nparams(), json_body={
        'location': region,
        'properties': {
            'addressSpace': {'addressPrefixes': ['10.10.0.0/16']},
            'subnets': [{
                'name': 'default',
                'properties': {'addressPrefix': '10.10.0.0/24'},
            }],
        },
    })


def run_instances(region: str, cluster_name_on_cloud: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    pc = config.provider_config
    pc.setdefault('region', region)
    sub = _sub(pc)
    rg = _rg(cluster_name_on_cloud)
    client = azure_adaptor.client()
    nc = {**pc, **config.node_config}

    try:
        client.request('PUT', _rg_path(sub, rg),
                       params={'api-version': _RG_API},
                       json_body={'location': region,
                                  'tags': {CLUSTER_TAG:
                                           cluster_name_on_cloud}})
        _ensure_network(client, sub, rg, region)

        existing = {vm['name']: vm for vm in _list_vms(client, sub, rg)}
        created: List[str] = []
        resumed: List[str] = []
        for i in range(config.count):
            name = f'{cluster_name_on_cloud}-{i}'
            vm = existing.get(name)
            state = _vm_state(vm) if vm else None
            if state in ('running', 'pending'):
                continue
            if state == 'stopped' and config.resume_stopped_nodes:
                client.request(
                    'POST',
                    _compute(sub, rg, 'virtualMachines', name) + '/start',
                    params=_cparams())
                resumed.append(name)
                continue
            if state is not None:
                # stopped-without-resume / stopping: re-PUTting the VM
                # model would NOT power it on — refuse, like AWS.
                raise exceptions.ProvisionError(
                    f'Node {i} of {cluster_name_on_cloud} is {state}; '
                    'cannot make progress.')
            _create_vm(client, sub, rg, region, name, i,
                       cluster_name_on_cloud, config, nc)
            created.append(name)
        _wait_running(client, sub, rg,
                      timeout=float(pc.get('provision_timeout', 900)))
    except azure_adaptor.AzureApiError as e:
        raise azure_adaptor.classify_api_error(e) from e
    return common.ProvisionRecord(
        provider_name='azure', region=region, zone=None,
        cluster_name_on_cloud=cluster_name_on_cloud,
        head_instance_id=f'{cluster_name_on_cloud}-0',
        created_instance_ids=created, resumed_instance_ids=resumed)


def _create_vm(client, sub: str, rg: str, region: str, name: str,
               index: int, cluster_name_on_cloud: str,
               config: common.ProvisionConfig,
               nc: Dict[str, Any]) -> None:
    subnet_id = (f'{_network(sub, rg, "virtualNetworks", "skytpu-vnet")}'
                 f'/subnets/default')
    nsg_id = _network(sub, rg, 'networkSecurityGroups', 'skytpu-nsg')
    client.request('PUT', _network(sub, rg, 'publicIPAddresses',
                                   f'{name}-ip'),
                   params=_nparams(), json_body={
        'location': region,
        'properties': {'publicIPAllocationMethod': 'Static'},
    })
    client.request('PUT', _network(sub, rg, 'networkInterfaces',
                                   f'{name}-nic'),
                   params=_nparams(), json_body={
        'location': region,
        'properties': {
            'networkSecurityGroup': {'id': nsg_id},
            'ipConfigurations': [{
                'name': 'primary',
                'properties': {
                    'subnet': {'id': subnet_id},
                    'publicIPAddress': {
                        'id': _network(sub, rg, 'publicIPAddresses',
                                       f'{name}-ip')},
                },
            }],
        },
    })
    auth = config.authentication_config
    ssh_user = auth.get('ssh_user', 'skytpu')
    body = {
        'location': region,
        'tags': {
            CLUSTER_TAG: cluster_name_on_cloud,
            HEAD_TAG: 'true' if index == 0 else 'false',
            INDEX_TAG: str(index),
            **config.tags,
        },
        'properties': {
            'hardwareProfile': {
                'vmSize': nc.get('instance_type', 'Standard_D8s_v5')},
            'storageProfile': {
                # image_id: a full ARM image/gallery resource id.
                'imageReference': ({'id': nc['image_id']}
                                   if nc.get('image_id')
                                   else nc.get('image_reference',
                                               _DEFAULT_IMAGE)),
                'osDisk': {
                    'createOption': 'FromImage',
                    'diskSizeGB': int(nc.get('disk_size', 256)),
                    'managedDisk': {
                        'storageAccountType': 'Premium_LRS'},
                },
            },
            'osProfile': {
                'computerName': name,
                'adminUsername': ssh_user,
                'linuxConfiguration': {
                    'disablePasswordAuthentication': True,
                    'ssh': {'publicKeys': [{
                        'path': f'/home/{ssh_user}/.ssh/authorized_keys',
                        'keyData': common.require_public_key(auth),
                    }]},
                },
            },
            'networkProfile': {'networkInterfaces': [{
                'id': _network(sub, rg, 'networkInterfaces',
                               f'{name}-nic')}]},
        },
    }
    if nc.get('use_spot'):
        body['properties']['priority'] = 'Spot'
        body['properties']['evictionPolicy'] = 'Deallocate'
    client.request('PUT', _compute(sub, rg, 'virtualMachines', name),
                   params=_cparams(), json_body=body)


def _list_vms(client, sub: str, rg: str) -> List[Dict[str, Any]]:
    try:
        resp = client.request(
            'GET', _compute(sub, rg, 'virtualMachines'),
            params={**_cparams(), '$expand': 'instanceView'})
    except azure_adaptor.AzureApiError as e:
        if e.status == 404 or e.code == 'ResourceGroupNotFound':
            return []
        raise
    return resp.get('value') or []


def _vm_state(vm: Dict[str, Any]) -> str:
    statuses = (vm.get('properties', {}).get('instanceView', {})
                .get('statuses') or [])
    for status in statuses:
        mapped = _POWER_MAP.get(status.get('code', ''))
        if mapped:
            return mapped
    prov = vm.get('properties', {}).get('provisioningState', 'Creating')
    return 'running' if prov == 'Succeeded' else 'pending'


def _wait_running(client, sub: str, rg: str,
                  timeout: float = 900.0) -> None:
    deadline = time.time() + timeout
    while True:
        vms = _list_vms(client, sub, rg)
        if vms and all(_vm_state(v) == 'running' for v in vms):
            return
        if time.time() > deadline:
            raise exceptions.ProvisionError(
                'Timed out waiting for running: '
                f'{ {v["name"]: _vm_state(v) for v in vms} }')
        time.sleep(5.0)


def wait_instances(region: str, cluster_name_on_cloud: str,
                   state: Optional[str] = None) -> None:
    # run_instances already waits for its VMs (the subscription id
    # lives in provider_config, which this hook doesn't receive).
    del region, cluster_name_on_cloud, state


def stop_instances(cluster_name_on_cloud: str,
                   provider_config: Dict[str, Any]) -> None:
    sub = _sub(provider_config)
    rg = _rg(cluster_name_on_cloud)
    client = azure_adaptor.client()
    for vm in _list_vms(client, sub, rg):
        if _vm_state(vm) == 'running':
            client.request(
                'POST',
                _compute(sub, rg, 'virtualMachines', vm['name']) +
                '/deallocate', params=_cparams())


def terminate_instances(cluster_name_on_cloud: str,
                        provider_config: Dict[str, Any]) -> None:
    """Delete the whole resource group: VMs, NICs, IPs, disks — gone."""
    sub = _sub(provider_config)
    client = azure_adaptor.client()
    try:
        client.request('DELETE',
                       _rg_path(sub, _rg(cluster_name_on_cloud)),
                       params={'api-version': _RG_API})
    except azure_adaptor.AzureApiError as e:
        if e.status != 404 and e.code != 'ResourceGroupNotFound':
            raise


def query_instances(cluster_name_on_cloud: str,
                    provider_config: Dict[str, Any]
                    ) -> Dict[str, Optional[str]]:
    sub = _sub(provider_config)
    client = azure_adaptor.client()
    return {vm['name']: _vm_state(vm)
            for vm in _list_vms(client, sub,
                                _rg(cluster_name_on_cloud))}


def get_cluster_info(region: str, cluster_name_on_cloud: str,
                     provider_config: Dict[str, Any]) -> common.ClusterInfo:
    del region
    sub = _sub(provider_config)
    rg = _rg(cluster_name_on_cloud)
    client = azure_adaptor.client()
    instances: Dict[str, common.InstanceInfo] = {}
    head_id: Optional[str] = None
    for vm in _list_vms(client, sub, rg):
        if _vm_state(vm) != 'running':
            continue
        name = vm['name']
        nic = client.request(
            'GET', _network(sub, rg, 'networkInterfaces', f'{name}-nic'),
            params=_nparams())
        ipcfg = (nic.get('properties', {}).get('ipConfigurations')
                 or [{}])[0].get('properties', {})
        internal = ipcfg.get('privateIPAddress', '')
        external = None
        if ipcfg.get('publicIPAddress'):
            ip_res = client.request(
                'GET', _network(sub, rg, 'publicIPAddresses',
                                f'{name}-ip'), params=_nparams())
            external = ip_res.get('properties', {}).get('ipAddress')
        tags = vm.get('tags') or {}
        instances[name] = common.InstanceInfo(
            instance_id=name,
            hosts=[common.HostInfo(host_id=name, internal_ip=internal,
                                   external_ip=external)],
            status='running', tags=tags)
        if tags.get(HEAD_TAG) == 'true':
            head_id = name
    if head_id is None and instances:
        head_id = sorted(instances)[0]
    return common.ClusterInfo(
        instances=instances, head_instance_id=head_id,
        provider_name='azure', provider_config=provider_config,
        ssh_user=provider_config.get('ssh_user', 'skytpu'),
        ssh_private_key=provider_config.get('ssh_private_key'))


def open_ports(cluster_name_on_cloud: str, ports: List[str],
               provider_config: Dict[str, Any]) -> None:
    """Extra inbound rules on the cluster NSG."""
    sub = _sub(provider_config)
    rg = _rg(cluster_name_on_cloud)
    client = azure_adaptor.client()
    nsg = client.request('GET', _network(sub, rg,
                                         'networkSecurityGroups',
                                         'skytpu-nsg'),
                         params=_nparams())
    rules = nsg.get('properties', {}).get('securityRules', [])
    existing_names = {r.get('name') for r in rules}
    priority = 1100 + len(rules)
    added = 0
    for port in ports:
        lo, _, hi = str(port).partition('-')
        name = f'skytpu-port-{lo}'
        if name in existing_names:
            continue  # idempotent relaunch: rule already present
        rules.append({
            'name': name,
            'properties': {
                'priority': priority + added, 'direction': 'Inbound',
                'access': 'Allow', 'protocol': 'Tcp',
                'sourceAddressPrefix': '*', 'sourcePortRange': '*',
                'destinationAddressPrefix': '*',
                'destinationPortRange': f'{lo}-{hi}' if hi else lo,
            }})
        added += 1
    if not added:
        return
    client.request('PUT', _network(sub, rg, 'networkSecurityGroups',
                                   'skytpu-nsg'),
                   params=_nparams(), json_body={
        'location': nsg.get('location',
                            provider_config.get('region', '')),
        'properties': {'securityRules': rules},
    })


def get_command_runners(cluster_info: common.ClusterInfo):
    use_internal = bool(
        cluster_info.provider_config.get('use_internal_ips', False))
    return common.ssh_command_runners(cluster_info, 'skytpu',
                                      use_internal=use_internal)
