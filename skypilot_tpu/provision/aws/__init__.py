"""AWS EC2 provisioner — the second VM cloud proving the multi-cloud
abstraction.

Reference analog: sky/provision/aws/instance.py (1735 LoC, boto3).
Ours drives the EC2 Query API through the injectable adaptor client
(skypilot_tpu/adaptors/aws.py) with the same uniform provision
interface as GCP/Kubernetes: run/stop/terminate/query/get_cluster_info/
open_ports/get_command_runners. SSH keys ride cloud-init user-data (the
EC2 twin of GCP's ssh-keys metadata) so no ImportKeyPair state is
needed; a per-cluster security group carries SSH + opened ports.
"""
import base64
import logging
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.adaptors import aws as aws_adaptor
from skypilot_tpu.provision import common
from skypilot_tpu.utils import command_runner

logger = logging.getLogger(__name__)

CLUSTER_TAG = 'skytpu-cluster'
HEAD_TAG = 'skytpu-head'
INDEX_TAG = 'skytpu-index'

_STATE_MAP = {
    'pending': 'pending',
    'running': 'running',
    'shutting-down': 'terminating',
    'terminated': 'terminated',
    'stopping': 'stopping',
    'stopped': 'stopped',
}

# Ubuntu 22.04 LTS amd64 (public Canonical AMIs); overridable via
# resources.image_id / provider_config['image_id'].
DEFAULT_AMIS = {
    'us-east-1': 'ami-0557a15b87f6559cf',
    'us-east-2': 'ami-00eeedc4036573771',
    'us-west-2': 'ami-0efcece6bed30fd98',
    'eu-west-1': 'ami-0694d931cee176e7d',
    'ap-northeast-1': 'ami-0d52744d6551d851e',
}


def _region(pc: Dict[str, Any]) -> str:
    return pc['region']


def _instances(client, cluster_name_on_cloud: str,
               states: Optional[List[str]] = None) -> List[Dict[str, Any]]:
    extra = {'instance-state-name': states} if states else {}
    resp = client.call('DescribeInstances',
                       aws_adaptor.tag_filters(cluster_name_on_cloud,
                                               extra))
    out: List[Dict[str, Any]] = []
    for reservation in resp.get('reservationSet') or []:
        out.extend(reservation.get('instancesSet') or [])
    return out


def _tags(inst: Dict[str, Any]) -> Dict[str, str]:
    return {t['key']: t['value'] for t in inst.get('tagSet') or []}


def _state(inst: Dict[str, Any]) -> str:
    name = (inst.get('instanceState') or {}).get('name', 'pending')
    return _STATE_MAP.get(name, 'pending')


def _user_data(config: common.ProvisionConfig) -> str:
    """cloud-init that authorizes our deterministic SSH key."""
    auth = config.authentication_config
    user = auth.get('ssh_user', 'skytpu')
    pub = common.require_public_key(auth)
    return (f'#cloud-config\n'
            f'users:\n'
            f'  - name: {user}\n'
            f'    sudo: ALL=(ALL) NOPASSWD:ALL\n'
            f'    shell: /bin/bash\n'
            f'    ssh_authorized_keys:\n'
            f'      - {pub}\n')


def _default_vpc_id(client) -> str:
    resp = client.call('DescribeVpcs', {
        'Filter.1.Name': 'isDefault', 'Filter.1.Value.1': 'true'})
    vpcs = resp.get('vpcSet') or []
    if not vpcs:
        raise exceptions.ProvisionError(
            'No default VPC in region; set aws.vpc_id in config.')
    return vpcs[0]['vpcId']


def _ensure_security_group(client, cluster_name_on_cloud: str,
                           pc: Dict[str, Any]) -> str:
    """Per-cluster SG with SSH ingress; open_ports appends rules.

    Lookup is scoped to the target VPC — a same-named group in another
    VPC (e.g. after the user switches aws.vpc_id) must not be reused.
    """
    name = f'skytpu-{cluster_name_on_cloud}'
    vpc_id = pc.get('vpc_id') or _default_vpc_id(client)
    resp = client.call('DescribeSecurityGroups', {
        'Filter.1.Name': 'group-name', 'Filter.1.Value.1': name,
        'Filter.2.Name': 'vpc-id', 'Filter.2.Value.1': vpc_id})
    groups = resp.get('securityGroupInfo') or []
    if groups:
        return groups[0]['groupId']
    created = client.call('CreateSecurityGroup', {
        'GroupName': name, 'VpcId': vpc_id,
        'GroupDescription': f'skytpu cluster {cluster_name_on_cloud}'})
    group_id = created['groupId']
    _authorize_ports(client, group_id, ['22'])
    return group_id


def _authorize_ports(client, group_id: str, ports: List[str]) -> None:
    for i, port in enumerate(ports, 1):
        lo, _, hi = str(port).partition('-')
        try:
            client.call('AuthorizeSecurityGroupIngress', {
                'GroupId': group_id,
                'IpPermissions.1.IpProtocol': 'tcp',
                'IpPermissions.1.FromPort': lo,
                'IpPermissions.1.ToPort': hi or lo,
                'IpPermissions.1.IpRanges.1.CidrIp': '0.0.0.0/0',
            })
        except aws_adaptor.AwsApiError as e:
            if e.code != 'InvalidPermission.Duplicate':
                raise


def _image_id(config: common.ProvisionConfig, region: str) -> str:
    nc = {**config.provider_config, **config.node_config}
    image = nc.get('image_id')
    if image:
        return image
    image = DEFAULT_AMIS.get(region)
    if image is None:
        raise exceptions.ProvisionError(
            f'No default AMI known for region {region}; set image_id.')
    return image


def run_instances(region: str, cluster_name_on_cloud: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    pc = config.provider_config
    pc.setdefault('region', region)
    client = aws_adaptor.client(region)
    nc = {**pc, **config.node_config}

    existing: Dict[int, Dict[str, Any]] = {}
    for inst in _instances(client, cluster_name_on_cloud):
        if _state(inst) == 'terminated':
            continue
        try:
            existing[int(_tags(inst).get(INDEX_TAG, -1))] = inst
        except ValueError:
            continue

    group_id = _ensure_security_group(client, cluster_name_on_cloud, pc)
    created: List[str] = []
    resumed: List[str] = []
    head_instance_id: Optional[str] = None
    try:
        for i in range(config.count):
            inst = existing.get(i)
            status = _state(inst) if inst else None
            if status in ('running', 'pending'):
                pass
            elif status == 'stopped' and config.resume_stopped_nodes:
                client.call('StartInstances', {
                    'InstanceId.1': inst['instanceId']})
                resumed.append(inst['instanceId'])
            elif status is None:
                inst = _create_instance(client, config, i,
                                        cluster_name_on_cloud, region,
                                        group_id)
                created.append(inst['instanceId'])
            else:
                raise exceptions.ProvisionError(
                    f'Node {i} of {cluster_name_on_cloud} is {status}; '
                    'cannot make progress.')
            if i == 0:
                head_instance_id = inst['instanceId']
    except aws_adaptor.AwsApiError as e:
        raise aws_adaptor.classify_api_error(e) from e
    return common.ProvisionRecord(
        provider_name='aws', region=region,
        zone=nc.get('zone'), cluster_name_on_cloud=cluster_name_on_cloud,
        head_instance_id=head_instance_id,
        created_instance_ids=created, resumed_instance_ids=resumed)


def _create_instance(client, config: common.ProvisionConfig, index: int,
                     cluster_name_on_cloud: str, region: str,
                     group_id: str) -> Dict[str, Any]:
    nc = {**config.provider_config, **config.node_config}
    tags = {
        'Name': f'{cluster_name_on_cloud}-{index}',
        CLUSTER_TAG: cluster_name_on_cloud,
        HEAD_TAG: 'true' if index == 0 else 'false',
        INDEX_TAG: str(index),
        **config.tags,
    }
    params: Dict[str, str] = {
        'ImageId': _image_id(config, region),
        'InstanceType': nc.get('instance_type', 'm6i.2xlarge'),
        'MinCount': '1', 'MaxCount': '1',
        'SecurityGroupId.1': group_id,
        'UserData': base64.b64encode(
            _user_data(config).encode()).decode(),
        'BlockDeviceMapping.1.DeviceName': '/dev/sda1',
        'BlockDeviceMapping.1.Ebs.VolumeSize': str(
            nc.get('disk_size', 256)),
        'BlockDeviceMapping.1.Ebs.VolumeType': 'gp3',
        'TagSpecification.1.ResourceType': 'instance',
    }
    for j, (k, v) in enumerate(sorted(tags.items()), 1):
        params[f'TagSpecification.1.Tag.{j}.Key'] = k
        params[f'TagSpecification.1.Tag.{j}.Value'] = v
    if nc.get('zone'):
        params['Placement.AvailabilityZone'] = nc['zone']
    if nc.get('use_spot'):
        params['InstanceMarketOptions.MarketType'] = 'spot'
        params['InstanceMarketOptions.SpotOptions.SpotInstanceType'] = \
            'one-time'
        params['InstanceMarketOptions.SpotOptions.'
               'InstanceInterruptionBehavior'] = 'terminate'
    resp = client.call('RunInstances', params)
    instances = resp.get('instancesSet') or []
    if not instances:
        raise exceptions.ProvisionError(
            f'RunInstances returned no instance: {resp}')
    return instances[0]


def wait_instances(region: str, cluster_name_on_cloud: str,
                   state: Optional[str] = None,
                   timeout: float = 600.0) -> None:
    client = aws_adaptor.client(region)
    want = state or 'running'
    deadline = time.time() + timeout
    while True:
        instances = [i for i in _instances(client, cluster_name_on_cloud)
                     if _state(i) != 'terminated']
        if instances and all(_state(i) == want for i in instances):
            return
        if time.time() > deadline:
            states = {i['instanceId']: _state(i) for i in instances}
            raise exceptions.ProvisionError(
                f'Timed out waiting for {want}: {states}')
        time.sleep(2.0)


def stop_instances(cluster_name_on_cloud: str,
                   provider_config: Dict[str, Any]) -> None:
    client = aws_adaptor.client(_region(provider_config))
    ids = [i['instanceId']
           for i in _instances(client, cluster_name_on_cloud,
                               states=['running', 'pending'])]
    if ids:
        client.call('StopInstances', aws_adaptor.flat_params(
            'InstanceId', ids))


def terminate_instances(cluster_name_on_cloud: str,
                        provider_config: Dict[str, Any]) -> None:
    client = aws_adaptor.client(_region(provider_config))
    ids = [i['instanceId']
           for i in _instances(client, cluster_name_on_cloud)
           if _state(i) != 'terminated']
    if ids:
        client.call('TerminateInstances', aws_adaptor.flat_params(
            'InstanceId', ids))
    # Best-effort SG cleanup (fails with DependencyViolation until
    # instances fully terminate; harmless to leave behind). Scoped to
    # the configured VPC when known, and per-group so one failure does
    # not leak the others.
    name = f'skytpu-{cluster_name_on_cloud}'
    params = {'Filter.1.Name': 'group-name', 'Filter.1.Value.1': name}
    if provider_config.get('vpc_id'):
        params['Filter.2.Name'] = 'vpc-id'
        params['Filter.2.Value.1'] = provider_config['vpc_id']
    try:
        resp = client.call('DescribeSecurityGroups', params)
    except aws_adaptor.AwsApiError:
        return
    for group in resp.get('securityGroupInfo') or []:
        try:
            client.call('DeleteSecurityGroup',
                        {'GroupId': group['groupId']})
        except aws_adaptor.AwsApiError:
            pass


def query_instances(cluster_name_on_cloud: str,
                    provider_config: Dict[str, Any]
                    ) -> Dict[str, Optional[str]]:
    client = aws_adaptor.client(_region(provider_config))
    out: Dict[str, Optional[str]] = {}
    for inst in _instances(client, cluster_name_on_cloud):
        state = _state(inst)
        if state == 'terminated':
            continue
        out[inst['instanceId']] = {
            'terminating': 'stopping'}.get(state, state)
    return out


def get_cluster_info(region: str, cluster_name_on_cloud: str,
                     provider_config: Dict[str, Any]) -> common.ClusterInfo:
    client = aws_adaptor.client(region or _region(provider_config))
    instances: Dict[str, common.InstanceInfo] = {}
    head_id: Optional[str] = None
    ordered = sorted(
        (i for i in _instances(client, cluster_name_on_cloud,
                               states=['running'])),
        key=lambda i: int(_tags(i).get(INDEX_TAG, 1 << 30)))
    for inst in ordered:
        iid = inst['instanceId']
        instances[iid] = common.InstanceInfo(
            instance_id=iid,
            hosts=[common.HostInfo(
                host_id=iid,
                internal_ip=inst.get('privateIpAddress', ''),
                external_ip=inst.get('ipAddress') or None)],
            status='running', tags=_tags(inst))
        if _tags(inst).get(HEAD_TAG) == 'true':
            head_id = iid
    if head_id is None and instances:
        head_id = next(iter(instances))
    return common.ClusterInfo(
        instances=instances, head_instance_id=head_id,
        provider_name='aws', provider_config=provider_config,
        ssh_user=provider_config.get('ssh_user', 'skytpu'),
        ssh_private_key=provider_config.get('ssh_private_key'))


def open_ports(cluster_name_on_cloud: str, ports: List[str],
               provider_config: Dict[str, Any]) -> None:
    client = aws_adaptor.client(_region(provider_config))
    group_id = _ensure_security_group(client, cluster_name_on_cloud,
                                      provider_config)
    _authorize_ports(client, group_id, ports)


def get_command_runners(cluster_info: common.ClusterInfo
                        ) -> List[command_runner.CommandRunner]:
    runners: List[command_runner.CommandRunner] = []
    use_internal = bool(
        cluster_info.provider_config.get('use_internal_ips', False))
    for inst in cluster_info.ordered_instances():
        for host in inst.hosts:
            runners.append(command_runner.SSHCommandRunner(
                host.get_ip(use_internal=use_internal),
                user=cluster_info.ssh_user or 'skytpu',
                private_key=cluster_info.ssh_private_key,
                port=host.ssh_port))
    return runners
