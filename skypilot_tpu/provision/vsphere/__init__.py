"""vSphere provisioner — on-prem vCenter VMs on the shared REST
driver.

Reference analog: sky/provision/vsphere/instance.py (pyvmomi clone
from template + guest customization). The Automation API model: VMs
are CLONED from a template named in the resources' image_id (or
provider config `template`), carry our deterministic `<cluster>-<i>`
names, and power on after clone. Guest addresses come from
/guest/networking/interfaces, resolved in `_list` for powered-on VMs.
SSH identity is expected to be baked into the template (the standard
on-prem pattern); an optional customization spec name is passed
through.
"""
import re
from typing import Any, Dict, List

from skypilot_tpu import exceptions
from skypilot_tpu.adaptors import vsphere as vsphere_adaptor
from skypilot_tpu.provision import common, rest_driver

_VM = '/api/vcenter/vm'

_STATE_MAP = {
    'POWERED_ON': 'running',
    'POWERED_OFF': 'stopped',
    'SUSPENDED': 'stopped',
}


def _state(vm: Dict[str, Any]) -> str:
    return _STATE_MAP.get(str(vm.get('power_state', '')).upper(),
                          'pending')


def _guest_ip(client, vm: Dict[str, Any]) -> None:
    try:
        nics = client.request(
            'GET', f'{_VM}/{vm["vm"]}/guest/networking/interfaces')
    except vsphere_adaptor.RestApiError:
        return  # tools not ready yet: stay IP-less until next poll
    for nic in nics if isinstance(nics, list) else []:
        for addr in (nic.get('ip', {}).get('ip_addresses') or []):
            if addr.get('state') in (None, 'PREFERRED') and \
                    ':' not in addr.get('ip_address', ''):
                vm['ip_address'] = addr['ip_address']
                return


def _list(client, ctx: rest_driver.Ctx) -> List[Dict[str, Any]]:
    pattern = re.compile(re.escape(ctx.cluster) + r'-\d+$')
    resp = client.request('GET', _VM)
    vms = [v for v in (resp if isinstance(resp, list) else [])
           if pattern.fullmatch(v.get('name') or '')]
    for vm in vms:
        if _state(vm) == 'running' and 'ip_address' not in vm:
            _guest_ip(client, vm)
    return vms


def _create(client, ctx: rest_driver.Ctx, name: str) -> None:
    nc = ctx.nc
    template = nc.get('image_id') or nc.get('template')
    if not template:
        raise exceptions.ProvisionError(
            'vSphere needs a template VM: set image_id (template name) '
            'in resources or vsphere.template in config.')
    body: Dict[str, Any] = {
        'source': template,
        'name': name,
        'power_on': True,
    }
    placement = {
        key: nc[key] for key in ('folder', 'resource_pool',
                                 'datastore', 'cluster', 'host')
        if nc.get(key)
    }
    if placement:
        body['placement'] = placement
    if nc.get('customization_spec'):
        body['customization_spec'] = nc['customization_spec']
    client.request('POST', _VM, params={'action': 'clone'},
                   json_body=body)


def _power(client, vm_id: str, action: str) -> None:
    client.request('POST', f'{_VM}/{vm_id}/power',
                   params={'action': action})


def _terminate(client, ctx: rest_driver.Ctx, vm: Dict[str, Any]) -> None:
    if _state(vm) == 'running':
        _power(client, vm['vm'], 'stop')  # cannot delete a live VM
    client.request('DELETE', f'{_VM}/{vm["vm"]}')


_SPEC = rest_driver.RestVmSpec(
    provider='vsphere',
    adaptor=vsphere_adaptor,
    ssh_user='ubuntu',
    list_instances=_list,
    state=_state,
    name_of=lambda vm: vm['name'],
    create=_create,
    host_info=lambda vm: common.HostInfo(
        host_id=vm['vm'],
        internal_ip=vm.get('ip_address', ''),
        external_ip=vm.get('ip_address')),
    terminate=_terminate,
    stop=lambda client, ctx, vm: _power(client, vm['vm'], 'stop'),
    resume=lambda client, ctx, vm: _power(client, vm['vm'], 'start'),
)

rest_driver.RestVmDriver(_SPEC).export(globals())
