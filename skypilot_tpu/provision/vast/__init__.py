"""Vast.ai provisioner — GPU market on the shared REST driver.

Reference analog: sky/provision/vast/instance.py. Vast is an OFFER
MARKET, not a fleet API: capacity is found by searching bundles
(offers), and an instance is created by accepting an offer id ('ask').
Placement therefore re-searches on every launch; a vanished offer is
a CapacityError so the failover engine retries with the next one.
Labels carry our deterministic `<cluster>-<i>` identity.
"""
import re
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.adaptors import vast as vast_adaptor
from skypilot_tpu.provision import common, rest_driver

_STATE_MAP = {
    'created': 'pending',
    'loading': 'pending',
    'running': 'running',
    'stopping': 'stopping',
    'stopped': 'stopped',
    'exited': 'stopped',
    'offline': 'terminated',
}


def _state(inst: Dict[str, Any]) -> str:
    status = (inst.get('actual_status')
              or inst.get('intended_status') or '')
    return _STATE_MAP.get(str(status).lower(), 'pending')


def _list(client, ctx: rest_driver.Ctx) -> List[Dict[str, Any]]:
    pattern = re.compile(re.escape(ctx.cluster) + r'-\d+$')
    resp = client.request('GET', '/api/v0/instances/')
    return [i for i in resp.get('instances', [])
            if pattern.fullmatch(i.get('label') or '')]


# Catalog accelerator names -> Vast's live gpu_name vocabulary (the
# market names cards with spaces and interface suffixes).
_GPU_NAME_MAP = {
    'RTX4090': 'RTX 4090',
    'RTX3090': 'RTX 3090',
    'RTXA6000': 'RTX A6000',
    'A100-80GB': 'A100 SXM4',
    'A100': 'A100 PCIE',
    'H100': 'H100 SXM',
    'H200': 'H200',
    'L40S': 'L40S',
}


def search_offers(client, gpu_name: str, gpu_count: int,
                  region: Optional[str] = None) -> List[Dict[str, Any]]:
    """Rentable offers for the GPU shape, cheapest first."""
    query: Dict[str, Any] = {
        'gpu_name': {'eq': _GPU_NAME_MAP.get(gpu_name, gpu_name)},
        'num_gpus': {'eq': gpu_count},
        'rentable': {'eq': True},
        'order': [['dph_total', 'asc']],
        'type': 'on-demand',
    }
    if region:
        query['geolocation'] = {'eq': region}
    resp = client.request('PUT', '/api/v0/bundles/',
                          json_body={'q': query})
    return resp.get('offers', [])


def _create(client, ctx: rest_driver.Ctx, name: str) -> None:
    """Accept the cheapest live offer for the GPU shape."""
    nc = ctx.nc
    offers = search_offers(
        client, nc.get('gpu_type', ''), int(nc.get('gpu_count', 1)),
        ctx.region if ctx.region != 'any' else None)
    if not offers:
        raise exceptions.CapacityError(
            f'Vast: no rentable offers for '
            f'{nc.get("gpu_type")}:{nc.get("gpu_count")} in '
            f'{ctx.region}')
    ask_id = offers[0]['id']
    client.request('PUT', f'/api/v0/asks/{ask_id}/', json_body={
        'client_id': 'me',
        'image': nc.get('image_id') or 'ubuntu:22.04',
        'label': name,
        # mkdir first: stock container images ship without ~/.ssh.
        'onstart': ('mkdir -p ~/.ssh && echo "'
                    + common.require_public_key(
                        ctx.config.authentication_config)
                    + '" >> ~/.ssh/authorized_keys'),
        'runtype': 'ssh',
        'disk': float(nc.get('disk_size', 64)),
    })


_SPEC = rest_driver.RestVmSpec(
    provider='vast',
    adaptor=vast_adaptor,
    ssh_user='root',
    list_instances=_list,
    state=_state,
    name_of=lambda inst: inst['label'],
    create=_create,
    host_info=lambda inst: common.HostInfo(
        host_id=str(inst['id']),
        internal_ip=inst.get('public_ipaddr', ''),
        external_ip=inst.get('public_ipaddr'),
        ssh_port=int(inst.get('ssh_port') or 22)),
    terminate=lambda client, ctx, inst: client.request(
        'DELETE', f'/api/v0/instances/{inst["id"]}/'),
    terminate_terminated=True,
    stop=lambda client, ctx, inst: client.request(
        'PUT', f'/api/v0/instances/{inst["id"]}/',
        json_body={'state': 'stopped'}),
    resume=lambda client, ctx, inst: client.request(
        'PUT', f'/api/v0/instances/{inst["id"]}/',
        json_body={'state': 'running'}),
)

rest_driver.RestVmDriver(_SPEC).export(globals())
