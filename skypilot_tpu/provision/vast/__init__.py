"""Vast.ai provisioner — GPU market behind the uniform interface.

Reference analog: sky/provision/vast/instance.py. Vast is an OFFER
MARKET, not a fleet API: capacity is found by searching bundles
(offers), and an instance is created by accepting an offer id ('ask').
Placement therefore re-searches on every launch; a vanished offer is
a CapacityError so the failover engine retries with the next one.
Labels carry our deterministic `<cluster>-<i>` identity.
"""
import logging
import re
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.adaptors import vast as vast_adaptor
from skypilot_tpu.provision import common

logger = logging.getLogger(__name__)

_STATE_MAP = {
    'created': 'pending',
    'loading': 'pending',
    'running': 'running',
    'stopping': 'stopping',
    'stopped': 'stopped',
    'exited': 'stopped',
    'offline': 'terminated',
}


def _state(inst: Dict[str, Any]) -> str:
    status = (inst.get('actual_status')
              or inst.get('intended_status') or '')
    return _STATE_MAP.get(str(status).lower(), 'pending')


def _cluster_instances(client, cluster_name_on_cloud: str
                       ) -> List[Dict[str, Any]]:
    pattern = re.compile(re.escape(cluster_name_on_cloud) + r'-\d+$')
    resp = client.request('GET', '/api/v0/instances/')
    return [i for i in resp.get('instances', [])
            if pattern.fullmatch(i.get('label') or '')]


# Catalog accelerator names -> Vast's live gpu_name vocabulary (the
# market names cards with spaces and interface suffixes).
_GPU_NAME_MAP = {
    'RTX4090': 'RTX 4090',
    'RTX3090': 'RTX 3090',
    'RTXA6000': 'RTX A6000',
    'A100-80GB': 'A100 SXM4',
    'A100': 'A100 PCIE',
    'H100': 'H100 SXM',
    'H200': 'H200',
    'L40S': 'L40S',
}


def search_offers(client, gpu_name: str, gpu_count: int,
                  region: Optional[str] = None) -> List[Dict[str, Any]]:
    """Rentable offers for the GPU shape, cheapest first."""
    query: Dict[str, Any] = {
        'gpu_name': {'eq': _GPU_NAME_MAP.get(gpu_name, gpu_name)},
        'num_gpus': {'eq': gpu_count},
        'rentable': {'eq': True},
        'order': [['dph_total', 'asc']],
        'type': 'on-demand',
    }
    if region:
        query['geolocation'] = {'eq': region}
    resp = client.request('PUT', '/api/v0/bundles/',
                          json_body={'q': query})
    return resp.get('offers', [])


def run_instances(region: str, cluster_name_on_cloud: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    client = vast_adaptor.client()
    nc = {**config.provider_config, **config.node_config}
    existing = {i['label']: i for i in _cluster_instances(
        client, cluster_name_on_cloud)}
    created: List[str] = []
    resumed: List[str] = []
    try:
        for i in range(config.count):
            name = f'{cluster_name_on_cloud}-{i}'
            inst = existing.get(name)
            state = _state(inst) if inst else None
            if state in ('running', 'pending'):
                continue
            if state == 'stopped':
                if not config.resume_stopped_nodes:
                    raise exceptions.ProvisionError(
                        f'Instance {name} is stopped; pass '
                        'resume_stopped_nodes to restart it.')
                client.request('PUT',
                               f'/api/v0/instances/{inst["id"]}/',
                               json_body={'state': 'running'})
                resumed.append(name)
                continue
            common.refuse_unresumable(state, name)
            offers = search_offers(
                client, nc.get('gpu_type', ''),
                int(nc.get('gpu_count', 1)),
                region if region != 'any' else None)
            if not offers:
                raise exceptions.CapacityError(
                    f'Vast: no rentable offers for '
                    f'{nc.get("gpu_type")}:{nc.get("gpu_count")} '
                    f'in {region}')
            ask_id = offers[0]['id']
            client.request('PUT', f'/api/v0/asks/{ask_id}/',
                           json_body={
                               'client_id': 'me',
                               'image': nc.get('image_id') or
                               'ubuntu:22.04',
                               'label': name,
                               # mkdir first: stock container images
                               # ship without ~/.ssh.
                               'onstart': ('mkdir -p ~/.ssh && echo "'
                                           + common.require_public_key(
                                               config
                                               .authentication_config)
                                           + '" >> ~/.ssh/authorized_keys'
                                           ),
                               'runtype': 'ssh',
                               'disk': float(nc.get('disk_size', 64)),
                           })
            created.append(name)
        _wait_running(client, cluster_name_on_cloud, config.count,
                      timeout=float(config.provider_config.get(
                          'provision_timeout', 900)))
    except vast_adaptor.RestApiError as e:
        raise vast_adaptor.classify_api_error(e) from e
    return common.ProvisionRecord(
        provider_name='vast', region=region, zone=None,
        cluster_name_on_cloud=cluster_name_on_cloud,
        head_instance_id=f'{cluster_name_on_cloud}-0',
        created_instance_ids=created, resumed_instance_ids=resumed)


def _wait_running(client, cluster_name_on_cloud: str, count: int,
                  timeout: float = 900.0) -> None:
    common.wait_until_running(
        lambda: _cluster_instances(client, cluster_name_on_cloud),
        count, _state, lambda i: i['label'], timeout=timeout)


def wait_instances(region: str, cluster_name_on_cloud: str,
                   state: Optional[str] = None) -> None:
    del region, cluster_name_on_cloud, state  # run_instances waits


def stop_instances(cluster_name_on_cloud: str,
                   provider_config: Dict[str, Any]) -> None:
    client = vast_adaptor.client()
    for inst in _cluster_instances(client, cluster_name_on_cloud):
        if _state(inst) == 'running':
            client.request('PUT', f'/api/v0/instances/{inst["id"]}/',
                           json_body={'state': 'stopped'})


def terminate_instances(cluster_name_on_cloud: str,
                        provider_config: Dict[str, Any]) -> None:
    client = vast_adaptor.client()
    for inst in _cluster_instances(client, cluster_name_on_cloud):
        client.request('DELETE', f'/api/v0/instances/{inst["id"]}/')


def query_instances(cluster_name_on_cloud: str,
                    provider_config: Dict[str, Any]
                    ) -> Dict[str, Optional[str]]:
    client = vast_adaptor.client()
    out: Dict[str, Optional[str]] = {}
    for inst in _cluster_instances(client, cluster_name_on_cloud):
        state = _state(inst)
        if state == 'terminated':
            continue
        out[inst['label']] = state
    return out


def get_cluster_info(region: str, cluster_name_on_cloud: str,
                     provider_config: Dict[str, Any]) -> common.ClusterInfo:
    del region
    client = vast_adaptor.client()
    instances: Dict[str, common.InstanceInfo] = {}
    head_name = f'{cluster_name_on_cloud}-0'
    head_id: Optional[str] = None
    for inst in _cluster_instances(client, cluster_name_on_cloud):
        if _state(inst) != 'running':
            continue
        name = inst['label']
        instances[name] = common.InstanceInfo(
            instance_id=name,
            hosts=[common.HostInfo(
                host_id=str(inst['id']),
                internal_ip=inst.get('public_ipaddr', ''),
                external_ip=inst.get('public_ipaddr'),
                ssh_port=int(inst.get('ssh_port') or 22))],
            status='running', tags={})
        if name == head_name:
            head_id = name
    if head_id is None and instances:
        head_id = sorted(instances)[0]
    return common.ClusterInfo(
        instances=instances, head_instance_id=head_id,
        provider_name='vast', provider_config=provider_config,
        ssh_user='root',
        ssh_private_key=provider_config.get('ssh_private_key'))


def get_command_runners(cluster_info: common.ClusterInfo):
    return common.ssh_command_runners(cluster_info, 'root')
